"""Speculative decoding: a draft model proposes K tokens, the target model
verifies them in ONE chunked forward, and every accepted token costs the
target a fraction of a sequential decode step.

Added TPU-first scope beyond the reference (whose decode is strictly one
token per pipeline pass — /root/reference/models/qwen3/client/client.py:
244-266): bs=1 decode is HBM-bound on target weight reads, and verification
reads the target weights once per chunk instead of once per token, so with
acceptance rate a the target-read cost per emitted token drops toward
1/(1 + a*K) of sequential decode.

Design notes (what makes this cheap here):
  * the functional KV cache (core.cache.KVCache) masks validity by
    `length`, and chunk writes land at `length` — so REJECTION ROLLBACK IS
    FREE: keep the returned buffers, reset `length` to the accepted
    frontier, and stale slots are overwritten by the next chunk;
  * draft-scan + chunk-verify + accept-frontier run as ONE jitted step
    (lax arithmetic, no host sync inside); the host loop advances a whole
    accepted run per dispatch — fewer dispatches than per-token decode,
    which also matters on high-latency interconnects;
  * greedy mode reproduces the target's greedy decode EXACTLY, token for
    token, regardless of draft quality (the classic guarantee) — that
    exactness is the test;
  * sampled mode (temperature > 0) uses the standard rejection scheme over
    the warped (temperature/top-k/top-p) distributions: the emitted stream
    is DISTRIBUTED exactly as target-only sampling — pinned by a
    total-variation test against the target's warped probabilities.

Round invariant (B = 1):
  - both caches hold KV for the emitted stream x_0..x_{n-1}
  - x_n = `last_tok` is emitted but in NEITHER cache
  - the draft scan's first step ingests x_n, then drafts d_1..d_K
  - the target verifies chunk [x_n, d_1..d_K] in one forward; greedy[i] is
    its next token after chunk[:i+1], so d_{i+1} is accepted iff it equals
    greedy[i] and all earlier drafts were accepted
  - with m accepted drafts the round emits greedy[0..m] (m+1 tokens); the
    new pending token is greedy[m], and both caches roll forward exactly
    m+1 slots (the draft wrote only K slots, so on full acceptance it is
    one token behind and the next round's host loop ingests that token).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from inferd_tpu.config import ModelConfig, SamplingConfig
from inferd_tpu.core import sampling as samplib
from inferd_tpu.core.cache import KVCache
from inferd_tpu.core.generate import bucket_len
from inferd_tpu.core.spec_batch import SPEC_TOP_N
from inferd_tpu.models import qwen3

Params = Any


def self_draft(
    cfg: ModelConfig, params: Any, draft_layers: int
) -> Tuple[ModelConfig, Any]:
    """Layer-truncated SELF-draft: the target's own first `draft_layers`
    layers propose (no second checkpoint read). One definition shared by
    the local CLI (tools/generate) and the node's speculative /generate."""
    if not 0 < draft_layers < cfg.num_layers:
        raise ValueError(
            f"draft_layers must be in (0, {cfg.num_layers}), got {draft_layers}"
        )
    dcfg = cfg.with_layers(draft_layers)
    dparams = dict(params)
    dparams["layers"] = qwen3.slice_layers(params["layers"], 0, draft_layers)
    return dcfg, dparams


class SpeculativeEngine:
    """Greedy speculative decoding with a small draft model.

    Both models must share the tokenizer/vocab (e.g. qwen3-0.6b drafting
    for qwen3-8b). Decode state is two KV caches; rollback = length reset.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: Params,
        draft_cfg: ModelConfig,
        draft_params: Params,
        k: int = 4,
        max_len: int = 2048,
        sampling_cfg: Optional[SamplingConfig] = None,
        top_n: int = SPEC_TOP_N,
    ):
        if cfg.vocab_size != draft_cfg.vocab_size:
            raise ValueError(
                f"target/draft vocab mismatch: {cfg.vocab_size} vs "
                f"{draft_cfg.vocab_size} (they must share a tokenizer)"
            )
        from inferd_tpu.core.cache import RING_MARGIN

        if (cfg.sliding_window or draft_cfg.sliding_window) and k + 1 > RING_MARGIN:
            # ring KV safety: rejection rollback may reset length by up to
            # the verify-chunk depth, and stale ring slots stay outside
            # every window only while that depth is under the ring margin
            raise ValueError(
                f"speculative k={k} exceeds the sliding-window ring margin "
                f"({RING_MARGIN - 1} max for ring-KV models)"
            )
        self.cfg = cfg
        self.draft_cfg = draft_cfg
        self.params = params
        self.draft_params = draft_params
        self.k = k
        self.max_len = max_len
        self.sampling = sampling_cfg or SamplingConfig(temperature=0.0)

        self.top_n = top_n
        tcfg, dcfg, K = cfg, draft_cfg, k
        TOPN = top_n
        sc = self.sampling

        def _warped_probs(logits):  # [.., V] f32 -> the sampled distribution
            return samplib.warped_probs(logits, sc)

        @partial(jax.jit, donate_argnames=("tc", "dc"),
                 static_argnames=("want_lp",))
        def _prefill(tp, dp, tokens, n, tc: KVCache, dc: KVCache, key,
                     want_lp: bool = False):
            """Prefill BOTH models on the prompt; returns the target's next
            token (greedy, or sampled when temperature > 0) + caches."""
            tl, tc = qwen3.forward_cached(tp, tcfg, tokens, None, tc, jnp.int32(0), real_end=n)
            _, dc = qwen3.forward_cached(dp, dcfg, tokens, None, dc, jnp.int32(0), real_end=n)
            tc = dataclasses.replace(tc, length=n)
            dc = dataclasses.replace(dc, length=n)
            last = tl[jnp.arange(tokens.shape[0]), n - 1]
            if sc.temperature == 0.0:
                tok = jnp.argmax(last, axis=-1)
            else:
                tok = samplib.sample(last, key, sc.temperature, sc.top_k, sc.top_p, sc.min_p)
            tok = tok.astype(jnp.int32)
            # want_lp static: the plain greedy fast path never pays the
            # full-vocab log-softmax (each variant compiles separately)
            lp, ti, tls = (
                samplib.logprob_topn(last, tok, TOPN) if want_lp
                else (jnp.zeros((1,), jnp.float32),
                      jnp.zeros((1, 0), jnp.int32), jnp.zeros((1, 0), jnp.float32))
            )
            return tok, tc, dc, lp, ti, tls

        @partial(jax.jit, donate_argnames=("dc",))
        def _draft_ingest(dp, tok, dc: KVCache):
            """Cache catch-up: feed one already-emitted token through the
            draft (used after a fully-accepted round)."""
            _, nc = qwen3.forward_cached(dp, dcfg, tok[:, None], None, dc, dc.length)
            return dataclasses.replace(nc, length=dc.length + 1)

        @partial(jax.jit, donate_argnames=("tc", "dc"),
                 static_argnames=("want_lp",))
        def _spec_step(tp, dp, last_tok, tc: KVCache, dc: KVCache,
                       want_lp: bool = False):
            """One speculative round (see module docstring invariant).

            Returns (toks [K+1], n_new in [1, K+1], tc', dc'): toks[:n_new]
            are the emitted target-greedy tokens."""
            n = tc.length

            # -- draft: ingest x_n then K-1 self-fed greedy steps -----------
            def draft_body(carry, _):
                tok, c = carry
                lg, nc = qwen3.forward_cached(
                    dp, dcfg, tok[:, None], None, c, c.length
                )
                c = dataclasses.replace(nc, length=c.length + 1)
                ntok = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
                return (ntok, c), ntok

            (_, dc2), drafts = jax.lax.scan(
                draft_body, (last_tok, dc), None, length=K
            )  # drafts [K, B]: d_1..d_K; dc2.length == n + K

            # -- target: verify the whole chunk in one forward --------------
            chunk = jnp.concatenate([last_tok[None], drafts], axis=0).T  # [B, K+1]
            tl, tc2 = qwen3.forward_cached(tp, tcfg, chunk, None, tc, n)
            greedy = jnp.argmax(tl, axis=-1).astype(jnp.int32)  # [B, K+1]

            # -- target logprobs for the whole chunk: the TARGET model's
            # log-softmax at every verify position (the serving-API logprob
            # of each emitted token g[i]; positions past the accept frontier
            # are discarded host-side)
            lp_all, ti_all, tl_all = (
                samplib.logprob_topn(tl[0], greedy[0], TOPN) if want_lp
                else (jnp.zeros((K + 1,), jnp.float32),
                      jnp.zeros((K + 1, 0), jnp.int32),
                      jnp.zeros((K + 1, 0), jnp.float32))
            )  # [K+1], [K+1, N], [K+1, N]

            # -- accept frontier (B = 1) ------------------------------------
            d = drafts[:, 0]  # [K]
            g = greedy[0]  # [K+1]
            acc = jnp.cumprod((d == g[:K]).astype(jnp.int32))  # 1..1 0..0
            m = jnp.sum(acc)  # accepted draft count in [0, K]
            n_new = m + 1  # + the target's own correction/extension token

            # -- roll both caches to the accepted frontier (ring-safe: the
            # rollback depth is <= K < cache.RING_MARGIN, so stale ring
            # slots stay structurally outside every window)
            tc = dataclasses.replace(tc2, length=n + n_new)
            # draft slots n..n+K-1 hold [x_n, d_1..d_{K-1}]; the accepted
            # stream prefix occupies n..n+m, so the draft is exactly at the
            # frontier for m < K and one token behind for m == K
            dc2 = dataclasses.replace(dc2, length=n + jnp.minimum(n_new, K))
            return g, n_new, tc, dc2, lp_all, ti_all, tl_all

        @partial(jax.jit, donate_argnames=("tc", "dc"))
        def _spec_step_sampled(tp, dp, last_tok, tc: KVCache, dc: KVCache, rkey):
            """One sampled speculative round (standard rejection scheme,
            Leviathan et al. / Chen et al.): draft token d_i ~ p_i is
            accepted with prob min(1, q_i(d_i)/p_i(d_i)); the first
            rejection resamples from the residual norm(max(q_i - p_i, 0));
            full acceptance samples the target's extra position. The
            emitted stream is distributed EXACTLY as target-only sampling
            over the warped (temperature/top-k/top-p) distribution."""
            n = tc.length
            keys = jax.random.split(rkey, K + 2)
            draft_keys, akey, rskey = keys[:K], keys[K], keys[K + 1]

            def draft_body(carry, key):
                tok, c = carry
                lg, nc = qwen3.forward_cached(
                    dp, dcfg, tok[:, None], None, c, c.length
                )
                c = dataclasses.replace(nc, length=c.length + 1)
                wl = samplib.warped_logits(
                    lg[:, 0], sc.temperature, sc.top_k, sc.top_p, sc.min_p
                )  # [B, V]
                # categorical over the warped logits directly: the draw is
                # from exactly softmax(wl) — the same p the accept ratio
                # and residual use (no smoothing mismatch)
                ntok = jax.random.categorical(key, wl, axis=-1).astype(jnp.int32)
                return (ntok, c), (ntok, jax.nn.softmax(wl, axis=-1)[0])

            (_, dc2), (drafts, dprobs) = jax.lax.scan(
                draft_body, (last_tok, dc), draft_keys
            )  # drafts [K, B]; dprobs [K, V]

            chunk = jnp.concatenate([last_tok[None], drafts], axis=0).T  # [B, K+1]
            tl, tc2 = qwen3.forward_cached(tp, tcfg, chunk, None, tc, n)
            tprobs = _warped_probs(tl[0])  # [K+1, V]

            d = drafts[:, 0]  # [K]
            idx = jnp.arange(K)
            q_d = tprobs[idx, d]  # q_i(d_i)
            p_d = dprobs[idx, d]  # p_i(d_i) > 0 (d_i was sampled from p_i)
            u = jax.random.uniform(akey, (K,))
            # STRICT: u in [0,1) can be exactly 0, and `0 * p <= 0` would
            # accept a token with zero target probability; `<` rejects both
            # the q_d == 0 and p_d == 0 edges, matching min(1, q/p)
            ok = u * p_d < q_d  # accept wp min(1, q/p)
            acc = jnp.cumprod(ok.astype(jnp.int32))
            m = jnp.sum(acc)  # accepted draft count
            n_new = m + 1

            # correction distribution at the frontier: residual for m < K,
            # the target's extra position for m == K
            resid = jnp.maximum(tprobs[:K] - dprobs, 0.0)  # [K, V]
            rmass = jnp.sum(resid, axis=-1, keepdims=True)
            # q <= p everywhere can only happen when q == p; guard the
            # normalization and fall back to q itself
            resid = jnp.where(rmass > 1e-9, resid / jnp.maximum(rmass, 1e-30), tprobs[:K])
            corr = jnp.concatenate([resid, tprobs[K:]], axis=0)  # [K+1, V]
            corr_m = corr[m]
            extra = jax.random.categorical(
                rskey,
                jnp.where(corr_m > 0, jnp.log(jnp.maximum(corr_m, 1e-38)), -jnp.inf),
                axis=-1,
            ).astype(jnp.int32)

            toks = jnp.concatenate([d, jnp.zeros((1,), jnp.int32)]).at[m].set(extra)

            tc = dataclasses.replace(tc2, length=n + n_new)
            dc2 = dataclasses.replace(dc2, length=n + jnp.minimum(n_new, K))
            return toks, n_new, tc, dc2

        self._prefill = _prefill
        self._spec_step = _spec_step
        self._spec_step_sampled = _spec_step_sampled
        self._draft_ingest = _draft_ingest

    def generate(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        logprob_sink: Optional[List[float]] = None,
        top_sink: Optional[List] = None,
    ) -> Tuple[List[int], float]:
        """Generation; returns (tokens, draft_acceptance_rate). See
        generate_with_stats for the raw proposed/accepted counts (the
        serving layer's cumulative metrics need counts, not a rate — and
        returning them keeps the handoff atomic under concurrent
        generates on one cached engine; mutable instance attributes would
        race)."""
        out, rate, _, _ = self.generate_with_stats(
            prompt_ids, max_new_tokens, eos_token_id, seed,
            logprob_sink, top_sink,
        )
        return out, rate

    def generate_with_stats(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        logprob_sink: Optional[List[float]] = None,
        top_sink: Optional[List] = None,
        on_tokens=None,
    ) -> Tuple[List[int], float, int, int]:
        """Generation; returns (tokens, draft_acceptance_rate, drafted,
        accepted). `on_tokens` (optional sync callable) receives each
        ACCEPTED RUN (a list of token ids) the moment its round lands —
        the streaming hook; called from the caller's thread.

        temperature == 0 (default): token-exact with core.generate.Engine
        greedy decode on the target. temperature > 0: rejection-sampled —
        the output stream is DISTRIBUTED exactly as target-only sampling
        (not token-identical to any particular Engine key schedule).

        `logprob_sink`/`top_sink` (greedy mode only — the rejection-sampled
        step has no per-token logprob trail) collect the TARGET model's
        log-probability of each emitted token + its top-`self.top_n`
        alternatives, straight from the verify chunk's logits — identical
        to what a plain Engine run reports for the same tokens.
        """
        want_lp = logprob_sink is not None or top_sink is not None
        if want_lp and self.sampling.temperature > 0.0:
            raise ValueError(
                "speculative logprobs are greedy-only (the sampled "
                "rejection step has no per-token logprob trail)"
            )
        if max_new_tokens <= 0:
            # match Engine.generate: no prefill, no emission — a streamed
            # max_new_tokens=0 must not produce a phantom token line
            if logprob_sink is not None:
                logprob_sink.clear()
            if top_sink is not None:
                top_sink.clear()
            return [], 0.0, 0, 0
        if logprob_sink is not None:
            logprob_sink.clear()
        if top_sink is not None:
            top_sink.clear()

        def record(lp, ti, tl):
            if logprob_sink is not None:
                logprob_sink.append(float(lp))
            if top_sink is not None:
                top_sink.append(
                    (np.asarray(ti).tolist(), np.asarray(tl).tolist())
                )

        n = len(prompt_ids)
        b = bucket_len(n)
        tokens = jnp.asarray([list(prompt_ids) + [0] * (b - n)], jnp.int32)
        tc = KVCache.create(self.cfg, self.cfg.num_layers, 1, self.max_len)
        dc = KVCache.create(self.draft_cfg, self.draft_cfg.num_layers, 1, self.max_len)
        key, sub = jax.random.split(jax.random.PRNGKey(seed))
        tok, tc, dc, plp, pti, ptl = self._prefill(
            self.params, self.draft_params, tokens, jnp.int32(n), tc, dc, sub,
            want_lp,
        )
        sampled = self.sampling.temperature > 0.0

        out: List[int] = [int(tok[0])]
        if want_lp:
            record(plp[0], pti[0], ptl[0])
        if on_tokens is not None:
            on_tokens(out[:1])
        drafted = accepted = 0
        while len(out) < max_new_tokens and (
            eos_token_id is None or out[-1] != eos_token_id
        ):
            if int(tc.length) + self.k + 1 > self.max_len:
                break  # KV budget: a whole verify chunk must fit
            if int(dc.length) < int(tc.length):  # catch-up after full accept
                dc = self._draft_ingest(
                    self.draft_params, jnp.asarray([out[-2]], jnp.int32), dc
                )
            if sampled:
                key, sub = jax.random.split(key)
                toks, n_new, tc, dc = self._spec_step_sampled(
                    self.params, self.draft_params, tok, tc, dc, sub
                )
                lps = tis = tls = None
            else:
                toks, n_new, tc, dc, lps, tis, tls = self._spec_step(
                    self.params, self.draft_params, tok, tc, dc, want_lp
                )
            n_new = int(n_new)
            drafted += self.k
            accepted += n_new - 1
            run: List[int] = []
            for j, t in enumerate(np.asarray(toks[:n_new]).tolist()):
                out.append(int(t))
                run.append(int(t))
                if want_lp:
                    record(lps[j], tis[j], tls[j])
                if (eos_token_id is not None and t == eos_token_id) or len(
                    out
                ) >= max_new_tokens:
                    break
            if on_tokens is not None and run:
                on_tokens(run)
            tok = jnp.asarray([out[-1]], jnp.int32)
        if logprob_sink is not None:
            del logprob_sink[max_new_tokens:]
        if top_sink is not None:
            del top_sink[max_new_tokens:]
        return (
            out[:max_new_tokens], accepted / max(drafted, 1), drafted, accepted
        )
