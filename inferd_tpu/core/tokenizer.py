"""Tokenizer + chat template wrapper.

The reference tokenizes with HF `AutoTokenizer` + `apply_chat_template`
(/root/reference/models/qwen3/client/client.py:208-215) and Qwen2Tokenizer on
stage-0 nodes (/root/reference/petals/partitioned_models.py:110). This wraps
the same HF path when tokenizer files are available locally, and falls back
to a deterministic byte-level tokenizer (ids = bytes + specials) so the whole
framework — generation loop, swarm, benchmarks — runs in zero-egress
environments without tokenizer downloads.
"""

from __future__ import annotations

from typing import List, Optional, Sequence


class ByteTokenizer:
    """Byte-level fallback: token id = byte value; specials above 255.

    Implements the ChatML-ish surface the generation loop needs: encode,
    decode, a chat template, and an EOS id.
    """

    vocab_size = 259
    bos_token_id = 256
    eos_token_id = 257
    pad_token_id = 258

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8", errors="replace"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", errors="replace")

    def apply_chat_template(self, messages, add_generation_prompt: bool = True) -> List[int]:
        parts = []
        for m in messages:
            parts.append(f"<|{m['role']}|>\n{m['content']}\n")
        if add_generation_prompt:
            parts.append("<|assistant|>\n")
        return [self.bos_token_id] + self.encode("".join(parts))


class Tokenizer:
    """Facade: HF tokenizer when available locally, ByteTokenizer otherwise."""

    def __init__(self, model_name: Optional[str] = None):
        self.hf = None
        self.model_name = model_name
        if model_name:
            try:
                from transformers import AutoTokenizer

                self.hf = AutoTokenizer.from_pretrained(
                    model_name, local_files_only=True
                )
            except Exception as e:
                # Byte-level ids are meaningless against a real Qwen vocab —
                # never fall back silently.
                import logging

                logging.getLogger(__name__).warning(
                    "could not load HF tokenizer %r (%s: %s); falling back to "
                    "byte-level tokenizer — only sensible for toy/test models",
                    model_name, type(e).__name__, e,
                )
                self.hf = None
        self._fallback = ByteTokenizer()

    @property
    def eos_token_id(self) -> int:
        if self.hf is not None and self.hf.eos_token_id is not None:
            return self.hf.eos_token_id
        return self._fallback.eos_token_id

    def encode(self, text: str) -> List[int]:
        if self.hf is not None:
            return self.hf.encode(text)
        return self._fallback.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        if self.hf is not None:
            return self.hf.decode(ids, skip_special_tokens=True)
        return self._fallback.decode(ids)

    def apply_chat_template(self, messages, add_generation_prompt: bool = True) -> List[int]:
        if self.hf is not None:
            return self.hf.apply_chat_template(
                messages, add_generation_prompt=add_generation_prompt, tokenize=True
            )
        return self._fallback.apply_chat_template(messages, add_generation_prompt)
