"""Core inference machinery: functional KV cache, sampling, generation."""
