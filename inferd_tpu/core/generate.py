"""Single-process generation engine: jitted prefill + decode over a
functional KV cache.

Capability parity with the reference's client-side generation loop
(/root/reference/models/qwen3/client/client.py:204-287 — chat-template
prefill, per-token decode with absolute positions, server-held KV, sampling,
EOS/max-length stop), redesigned for XLA:

  * prompt lengths are padded to power-of-two buckets so each bucket
    compiles once (dynamic shapes would recompile every prompt length);
  * decode is one fused jit step: forward + temperature/top-k/top-p sample
    on-device, so the host loop only syncs one int per token;
  * `generate_scan` runs the whole decode as a `lax.scan` — a single
    dispatch for fixed-length generation, the TPU-friendly benchmark path.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from inferd_tpu.config import ModelConfig, SamplingConfig
from inferd_tpu.core.cache import KVCache, grow
from inferd_tpu.core import prefix as prefixlib
from inferd_tpu.core import sampling as samplib
from inferd_tpu.models import qwen3


def bucket_len(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class Engine:
    """Owns params + jitted step functions for one model on one device/mesh."""

    def __init__(
        self,
        cfg: ModelConfig,
        params,
        max_len: int = 2048,
        sampling_cfg: Optional[SamplingConfig] = None,
        ring_kv: Optional[bool] = None,
        max_pins: int = 4,
    ):
        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.sampling = sampling_cfg or SamplingConfig()
        # ring_kv=None auto-enables O(window) ring storage for sliding-
        # window models (core.cache); False forces the classic uniform
        # full-length layout (comparison/compat path)
        self.ring_kv = ring_kv

        # cache buffers are donated: each step's KV update reuses the input
        # buffers in place on TPU instead of allocating a fresh [L,B,T,n,d]
        # pair per token (callers always rebind to the returned cache)
        @partial(jax.jit, donate_argnames=("cache",))
        def _prefill(params, tokens, prompt_len, cache: KVCache):
            # tokens are padded to a bucket; positions run 0..S-1. Slots past
            # prompt_len hold garbage but are never attended: cache.length is
            # reset to prompt_len and decode overwrites them sequentially
            # (rings drop padded rows at write time via real_end).
            logits, nc = qwen3.forward_cached(
                params, cfg, tokens, None, cache, jnp.int32(0),
                real_end=prompt_len,
            )
            cache = dataclasses.replace(nc, length=prompt_len)
            last = logits[jnp.arange(tokens.shape[0]), prompt_len - 1]
            return last, cache

        @partial(jax.jit, donate_argnames=("cache",))
        def _prefill_at(params, tokens, start_pos, real_len, cache: KVCache):
            # prefill a chunk at an arbitrary offset (prefix-cache reuse:
            # the first start_pos positions are already in the cache)
            b, s = tokens.shape
            pos = start_pos + jnp.broadcast_to(jnp.arange(s), (b, s))
            logits, nc = qwen3.forward_cached(
                params, cfg, tokens, pos, cache, cache.length,
                real_end=cache.length + real_len,
            )
            cache = dataclasses.replace(nc, length=cache.length + real_len)
            last = logits[jnp.arange(b), real_len - 1]
            return last, cache

        @partial(jax.jit, donate_argnames=("cache",))
        def _decode(params, tok, cache: KVCache, key):
            pos = jnp.broadcast_to(cache.length, (tok.shape[0], 1))
            logits, nc = qwen3.forward_cached(
                params, cfg, tok, pos, cache, cache.length,
                real_end=cache.length + 1,
            )
            cache = dataclasses.replace(nc, length=cache.length + 1)
            next_tok = samplib.sample(
                logits[:, 0],
                key,
                self.sampling.temperature,
                self.sampling.top_k,
                self.sampling.top_p,
                self.sampling.min_p,
            )
            return next_tok, cache

        @partial(jax.jit, donate_argnames=("cache",), static_argnames=("top_n",))
        def _decode_lp(params, tok, cache: KVCache, key, top_n: int):
            # logprob-reporting decode: samples IDENTICALLY to _decode (same
            # key, same warper chain) and additionally returns the emitted
            # token's model log-probability + top-N alternatives, computed
            # on device (no [B, V] host transfer per step)
            pos = jnp.broadcast_to(cache.length, (tok.shape[0], 1))
            logits, nc = qwen3.forward_cached(
                params, cfg, tok, pos, cache, cache.length,
                real_end=cache.length + 1,
            )
            cache = dataclasses.replace(nc, length=cache.length + 1)
            row = logits[:, 0]
            next_tok = samplib.sample(
                row, key,
                self.sampling.temperature, self.sampling.top_k,
                self.sampling.top_p, self.sampling.min_p,
            )
            lp, top_ids, top_lps = samplib.logprob_topn(row, next_tok, top_n)
            return next_tok, cache, lp, top_ids, top_lps

        @partial(
            jax.jit, donate_argnames=("cache",),
            static_argnames=("s", "top_n", "want_lp"),
        )
        def _decode_chunk(params, tok, cache: KVCache, key, s: int,
                          top_n: int = 0, want_lp: bool = False):
            """`s` fused decode steps in ONE dispatch (the solo-engine
            analogue of BatchedEngine.decode_chunk): the in-graph key chain
            splits exactly like the host loop, so tokens are bit-identical
            to `s` calls of _decode. Returns (seq [s, B], cache, key',
            lps [s, B], top_ids [s, B, n], top_lps [s, B, n])."""

            def body(carry, _):
                tok, cache, key = carry
                key, sub = jax.random.split(key)
                pos = jnp.broadcast_to(cache.length, (tok.shape[0], 1))
                logits, nc = qwen3.forward_cached(
                    params, cfg, tok, pos, cache, cache.length,
                    real_end=cache.length + 1,
                )
                cache = dataclasses.replace(nc, length=cache.length + 1)
                row = logits[:, 0]
                ntok = samplib.sample(
                    row, sub,
                    self.sampling.temperature, self.sampling.top_k,
                    self.sampling.top_p, self.sampling.min_p,
                )
                b = row.shape[0]
                lp, ti, tl = (
                    samplib.logprob_topn(row, ntok, top_n) if want_lp
                    else (jnp.zeros((b,), jnp.float32),
                          jnp.zeros((b, 0), jnp.int32),
                          jnp.zeros((b, 0), jnp.float32))
                )
                return (ntok[:, None], cache, key), (ntok, lp, ti, tl)

            (tok, cache, key), (seq, lps, tis, tls) = jax.lax.scan(
                body, (tok, cache, key), None, length=s
            )
            return seq, cache, key, lps, tis, tls

        @partial(jax.jit, static_argnames=("max_len",))
        def _run_scan(params, tokens, prompt_len, step_keys, eos, max_len):
            # jit caches by (token shape, steps via step_keys shape, max_len)
            # — repeated benchmark calls with the same shapes reuse the
            # compiled executable.
            b = tokens.shape[0]
            logits, c = _prefill(
                params, tokens, prompt_len,
                KVCache.create(cfg, cfg.num_layers, b, max_len, ring=self.ring_kv),
            )
            tok = samplib.sample(
                logits, step_keys[0],
                self.sampling.temperature, self.sampling.top_k,
                self.sampling.top_p, self.sampling.min_p,
            )
            done = tok == eos

            def body(carry, step_key):
                tok, c, done = carry
                ntok, c = _decode(params, tok[:, None], c, step_key)
                ntok = jnp.where(done, tok, ntok)
                done = done | (ntok == eos)
                return (ntok, c, done), ntok

            (_, _, _), toks = jax.lax.scan(body, (tok, c, done), step_keys[1:])
            return jnp.concatenate([tok[:, None], toks.T], axis=1)

        self._prefill = _prefill
        self._prefill_at = _prefill_at
        self._decode = _decode
        self._decode_lp = _decode_lp
        self._decode_chunk = _decode_chunk
        self._run_scan = _run_scan
        # prefix cache: pinned prompt prefix -> (KV snapshot, last logits).
        # The serving-path analogue is session forking (runtime.executor
        # fork_session); here the snapshot lives in this process.
        self._pins: "OrderedDict[Tuple[int, ...], Tuple[KVCache, jax.Array]]" = (
            OrderedDict()
        )
        # LRU cap on pinned prefix snapshots — a constructor parameter
        # (CLI: tools/generate --max-pins) because each pin holds a whole
        # KV snapshot: prefix-cache pressure is a capacity decision, not a
        # constant
        if max_pins < 1:
            raise ValueError(f"max_pins must be >= 1, got {max_pins}")
        self.max_pins = max_pins

    @property
    def pins_resident(self) -> int:
        """Pinned prefix snapshots currently held — exported as the
        `pins.resident` gauge wherever an Engine serves behind /metrics."""
        return len(self._pins)

    def new_cache(self, batch: int, max_len: Optional[int] = None) -> KVCache:
        return KVCache.create(
            self.cfg, self.cfg.num_layers, batch, max_len or self.max_len,
            ring=self.ring_kv,
        )

    # -- prefix caching ------------------------------------------------------

    def pin_prefix(self, prefix_ids: Sequence[int]) -> None:
        """Prefill `prefix_ids` once and keep the KV snapshot; later
        `generate()` calls whose prompt starts with these ids reuse it
        instead of recomputing the prefix (the classic shared-system-prompt
        serving win). Snapshots are LRU-capped at `max_pins`."""
        ids = prefixlib.normalize_ids(prefix_ids)
        if ids in self._pins:
            self._pins.move_to_end(ids)
            return
        cache = KVCache.create(
            self.cfg, self.cfg.num_layers, 1, bucket_len(len(ids)),
            ring=self.ring_kv,
        )
        logits, cache = self.prefill(list(ids), cache)
        self._pins[ids] = (cache, logits)
        while len(self._pins) > self.max_pins:
            self._pins.popitem(last=False)

    def unpin_prefix(self, prefix_ids: Sequence[int]) -> None:
        self._pins.pop(tuple(int(t) for t in prefix_ids), None)

    def _longest_pin(self, prompt_ids: Sequence[int]):
        return prefixlib.longest_prefix_match(self._pins, prompt_ids)

    def _cache_from_pin(self, pinned: KVCache) -> KVCache:
        """Session cache seeded from a pinned snapshot. EVERY leaf a fresh
        buffer (rings and length included): the decode/prefill jits donate
        their cache argument, and any leaf shared with the pin would be
        destroyed on first reuse."""
        target = max(self.max_len, pinned.max_len)
        ln = jnp.copy(pinned.length)
        kl = None if pinned.k_loc is None else jnp.copy(pinned.k_loc)
        vl = None if pinned.v_loc is None else jnp.copy(pinned.v_loc)
        if pinned.max_len < target:
            g = grow(pinned, target)  # pad writes into fresh k/v buffers
            return KVCache(k=g.k, v=g.v, length=ln, k_loc=kl, v_loc=vl)
        return KVCache(
            k=jnp.copy(pinned.k), v=jnp.copy(pinned.v), length=ln,
            k_loc=kl, v_loc=vl,
        )

    def prefill(self, prompt_ids: Sequence[int], cache: KVCache) -> Tuple[jax.Array, KVCache]:
        """Pad to bucket, run prefill; returns (last-token logits [B,V], cache)."""
        n = len(prompt_ids)
        cache.ensure_room(n)
        b = min(bucket_len(n), cache.max_len)
        padded = list(prompt_ids) + [0] * (b - n)
        tokens = jnp.asarray([padded], dtype=jnp.int32)
        return self._prefill(self.params, tokens, jnp.int32(n), cache)

    def generate(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        logprob_sink: Optional[List[float]] = None,
        top_n: int = 0,
        top_sink: Optional[List[Tuple[List[int], List[float]]]] = None,
        chunk: int = 1,
    ) -> List[int]:
        """Host-loop generation with EOS stop. Returns new token ids.

        `logprob_sink` (optional list, cleared) collects each emitted
        token's model log-probability (log-softmax of the RAW logits);
        `top_sink` with `top_n > 0` additionally collects the top-N
        (ids, logprobs) alternatives per step — the serving-API logprob
        surface, computed on device. Tokens are bit-identical with or
        without the sinks (same sampler, same key schedule).

        `chunk` > 1 fuses up to that many decode steps per dispatch (one
        compiled scan instead of N host round trips — the solo analogue of
        BatchedEngine's fused decode; kills the per-step host RTT on
        remote/tunneled devices). Tokens are bit-identical to chunk=1: the
        in-graph key chain equals the host loop's, and an EOS mid-chunk
        just discards the chunk's tail (bounded waste, like the batched
        engine)."""
        if len(prompt_ids) == 0:
            raise ValueError("prompt_ids must be non-empty")
        steps = self.sampling.max_new_tokens if max_new_tokens is None else max_new_tokens
        if steps <= 0:
            return []
        pin = self._longest_pin(prompt_ids)
        if pin is not None:
            pcache, plogits = self._pins[pin]
            self._pins.move_to_end(pin)
            cache = self._cache_from_pin(pcache)
            rest = list(prompt_ids[len(pin):])
            if rest:
                cache.ensure_room(len(rest))
                b = min(bucket_len(len(rest)), cache.max_len - len(pin))
                tokens = jnp.asarray([rest + [0] * (b - len(rest))], jnp.int32)
                logits, cache = self._prefill_at(
                    self.params, tokens, jnp.int32(len(pin)),
                    jnp.int32(len(rest)), cache,
                )
            else:
                logits = plogits
        else:
            cache = self.new_cache(batch=1)
            logits, cache = self.prefill(prompt_ids, cache)
        want_lp = logprob_sink is not None or top_sink is not None
        if logprob_sink is not None:
            logprob_sink.clear()
        if top_sink is not None:
            top_sink.clear()

        def append(lp, ti, tl):
            # single sink-append path for the prefill and decode steps
            if logprob_sink is not None:
                logprob_sink.append(float(lp[0]))
            if top_sink is not None:
                top_sink.append(
                    (np.asarray(ti[0]).tolist(), np.asarray(tl[0]).tolist())
                )

        def record(row_logits, tok_arr):
            # host-side for the prefill step (its [B, V] logits are already
            # on the host path); decode steps use the device-side jit
            append(*samplib.logprob_topn(
                jnp.asarray(row_logits), jnp.asarray(tok_arr), top_n
            ))

        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        tok = samplib.sample(
            logits, sub, self.sampling.temperature, self.sampling.top_k,
            self.sampling.top_p, self.sampling.min_p,
        )
        if want_lp:
            record(logits, tok)
        out = [int(tok[0])]
        if eos_token_id is not None and out[-1] == eos_token_id:
            return out
        while len(out) < steps:
            room = self.max_len - int(cache.length)
            s = min(chunk, steps - len(out), max(room, 1))
            if s > 1:
                s = 1 << (s.bit_length() - 1)  # pow2: bounded compile set
            if s > 1:
                # no ensure_room: s <= room by construction above, and the
                # check would cost a blocking device read per chunk — the
                # RTT this path exists to amortize
                seq, cache, key, lps_a, tis_a, tls_a = self._decode_chunk(
                    self.params, tok[:, None], cache, key, s, top_n, want_lp,
                )
                # ONE transfer for everything the host loop reads — a
                # per-token fetch would reintroduce the RTTs the chunk
                # exists to amortize
                seq_np, lps_a, tis_a, tls_a = jax.device_get(
                    (seq, lps_a, tis_a, tls_a)
                )
                done = False
                for j in range(s):
                    t = int(seq_np[j, 0])
                    out.append(t)
                    if want_lp:
                        append(lps_a[j], tis_a[j], tls_a[j])
                    if (eos_token_id is not None and t == eos_token_id) or (
                        len(out) >= steps
                    ):
                        done = True
                        break
                if done:
                    break
                tok = jnp.asarray(seq_np[-1])
                continue
            cache.ensure_room(1)
            key, sub = jax.random.split(key)
            if want_lp:
                tok, cache, lp, ti, tl = self._decode_lp(
                    self.params, tok[:, None], cache, sub, top_n
                )
                append(lp, ti, tl)
            else:
                tok, cache = self._decode(self.params, tok[:, None], cache, sub)
            t = int(tok[0])
            out.append(t)
            if eos_token_id is not None and t == eos_token_id:
                break
        return out

    def generate_scan(
        self,
        prompt_tokens: jax.Array,  # [B, S] already padded/bucketed
        prompt_len: int,
        steps: int,
        seed: int = 0,
        eos_token_id: Optional[int] = None,
    ) -> jax.Array:
        """Fully-jitted fixed-length generation (decode loop as lax.scan).

        One XLA dispatch for the whole generation — the benchmark path.
        After EOS (if given) a sequence keeps emitting pad-like tokens but is
        marked done; returns [B, steps] generated ids.
        """
        max_len = bucket_len(prompt_tokens.shape[1] + steps)

        # Key schedule identical to the host loop (`generate`): chained
        # key, sub = split(key) per step — so both paths sample the same
        # tokens for the same seed.
        key = jax.random.PRNGKey(seed)
        subs = []
        for _ in range(steps):
            key, sub = jax.random.split(key)
            subs.append(sub)
        step_keys = jnp.stack(subs)

        eos = jnp.int32(-1 if eos_token_id is None else eos_token_id)
        return self._run_scan(
            self.params, prompt_tokens, jnp.int32(prompt_len), step_keys, eos, max_len
        )


def generate_text(
    engine: Engine,
    tokenizer,
    prompt: str,
    max_new_tokens: int = 64,
    seed: int = 0,
    chat: bool = True,
) -> str:
    """Convenience end-to-end text generation (reference client.py:204-287)."""
    if chat:
        ids = tokenizer.apply_chat_template(
            [{"role": "user", "content": prompt}], add_generation_prompt=True
        )
    else:
        ids = tokenizer.encode(prompt)
    out = engine.generate(ids, max_new_tokens, eos_token_id=tokenizer.eos_token_id, seed=seed)
    return tokenizer.decode(out)
