"""Functional per-session KV cache.

Replaces the reference's server-side mutable `DynamicCache` keyed by session
id (/root/reference/models/qwen3/server/qwen3_server_module.py:220,253) with
an explicit, preallocated, fixed-shape buffer threaded through jitted calls —
the TPU-idiomatic design: XLA sees one static shape per (batch, max_len)
bucket instead of a shape that grows every token (which would trigger a
recompile per step).

Layout: k/v are [num_global_layers, batch, max_len, num_kv_heads, head_dim];
`length` is the number of populated positions. Overflow is checked host-side
(`ensure_room`) because in-jit dynamic_update_slice clamps silently (see
models/qwen3.decoder_layer contract).

Sliding-window models (Gemma-2, GPT-OSS) additionally carry RING buffers
`k_loc`/`v_loc` [num_sliding_layers, batch, ring, kv, d] for their sliding
(even-global-index) layers: a sliding layer never attends past its window,
so its storage is O(window), not O(context) — position p lives at slot
p % ring until position p + ring overwrites it. `ring = round16(window) +
RING_MARGIN`; the margin is what makes speculative rollback and bounded
fork-truncation safe (models/qwen3._ring_attend_update documents the
aliasing invariant). For non-sliding models `k_loc`/`v_loc` are None and
the layout is exactly the classic single-buffer one.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from inferd_tpu.config import ModelConfig

# Extra ring slots past the (16-rounded) window. Bounds how far "newer"
# data may sit in a slot whose formula position is already inside some
# window: speculative rollback depth and fork truncation depth must both
# stay under this margin (enforced at those call sites).
RING_MARGIN = 64


def ring_slots(cfg: ModelConfig) -> int:
    """Ring length for sliding layers: 16-rounded window + safety margin."""
    return (int(cfg.sliding_window) + 15) // 16 * 16 + RING_MARGIN


def sliding_layer_ids(
    cfg: ModelConfig, num_layers: int, layer_offset: int
) -> List[int]:
    """Stack-local indices of the SLIDING layers (static python): global
    layer index (layer_offset + i) even — the Gemma-2/GPT-OSS alternation
    (models/qwen3.layer_windows)."""
    if not cfg.sliding_window:
        return []
    return [i for i in range(num_layers) if (layer_offset + i) % 2 == 0]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: jax.Array  # [Lg, B, T, Nkv, D] global (full-length) layers
    v: jax.Array  # [Lg, B, T, Nkv, D]
    length: jax.Array  # int32 scalar: populated positions
    k_loc: Optional[jax.Array] = None  # [Ll, B, R, Nkv, D] sliding-layer rings
    v_loc: Optional[jax.Array] = None

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def batch(self) -> int:
        return self.k.shape[1]

    @property
    def ring(self) -> Optional[int]:
        return None if self.k_loc is None else self.k_loc.shape[2]

    @staticmethod
    def create(
        cfg: ModelConfig,
        num_layers: int,
        batch: int,
        max_len: int,
        dtype=None,
        layer_offset: int = 0,
        ring: Optional[bool] = None,
    ) -> "KVCache":
        """ring=None auto-enables ring storage for sliding-window configs;
        ring=False forces the classic uniform full-length layout (the
        comparison/compat path — also what executors with a TRACED layer
        offset must use)."""
        dt = dtype or cfg.kv_jnp_dtype
        use_ring = cfg.sliding_window > 0 if ring is None else (
            ring and cfg.sliding_window > 0
        )
        loc = sliding_layer_ids(cfg, num_layers, layer_offset) if use_ring else []
        if not loc:  # uniform layout (forced, no window, or global-only slice)
            shape = (num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
            return KVCache(
                k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt), length=jnp.int32(0)
            )
        lg = num_layers - len(loc)
        r = ring_slots(cfg)
        gshape = (lg, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
        lshape = (len(loc), batch, r, cfg.num_kv_heads, cfg.head_dim)
        return KVCache(
            k=jnp.zeros(gshape, dt),
            v=jnp.zeros(gshape, dt),
            length=jnp.int32(0),
            k_loc=jnp.zeros(lshape, dt),
            v_loc=jnp.zeros(lshape, dt),
        )

    def ensure_room(self, new_tokens: int, owner: Optional[str] = None) -> None:
        """Host-side overflow guard — call before dispatching a jitted step.
        Rings never overflow (they wrap); the global buffers bound growth.

        `owner` names the session/lane this cache serves; it rides the
        raised BufferError so the error a client sees and the kv.overflow
        journal event the node records carry the same identity."""
        used = int(self.length)
        if used + new_tokens > self.max_len:
            who = f" ({owner})" if owner else ""
            raise BufferError(
                f"KV cache overflow{who}: {used} used + {new_tokens} new > "
                f"{self.max_len}"
            )

    def updated(self, k: jax.Array, v: jax.Array, new_tokens) -> "KVCache":
        """New cache with written buffers and advanced length (pure)."""
        return KVCache(
            k=k, v=v, length=self.length + new_tokens,
            k_loc=self.k_loc, v_loc=self.v_loc,
        )


def lane_slice(cache: KVCache, lane) -> KVCache:
    """One lane's KVCache view, [.., 1, ..] on the batch axis (global +
    ring buffers). Shared by the lane-indexed engines (core.batch prefill,
    core.spec_batch draft prefill) so the ring-buffer field handling lives
    in exactly one place."""
    sl = lambda a: jax.lax.dynamic_slice_in_dim(a, lane, 1, axis=1)
    return KVCache(
        k=sl(cache.k), v=sl(cache.v), length=cache.length,
        k_loc=None if cache.k_loc is None else sl(cache.k_loc),
        v_loc=None if cache.v_loc is None else sl(cache.v_loc),
    )


def lane_write(cache: KVCache, lane, nc: KVCache) -> KVCache:
    """Write a lane_slice-shaped cache back into `lane` (inverse of
    lane_slice; in-place under donation)."""
    up = lambda a, b: jax.lax.dynamic_update_slice_in_dim(a, b, lane, axis=1)
    return KVCache(
        k=up(cache.k, nc.k), v=up(cache.v, nc.v), length=cache.length,
        k_loc=None if cache.k_loc is None else up(cache.k_loc, nc.k_loc),
        v_loc=None if cache.v_loc is None else up(cache.v_loc, nc.v_loc),
    )


# ---------------------------------------------------------------------------
# Paged KV: block pool + block tables (vLLM's PagedAttention lesson,
# redesigned for jit-static shapes)
# ---------------------------------------------------------------------------
#
# The dense lane slab ([layers, lanes, max_len, ...]) charges every lane the
# worst-case context: a 40-token chat reserves the same HBM as a 4k-token
# document. The paged layout stores K/V in a pool of fixed-size BLOCKS
# ([layers, num_blocks, block_size, ...]) and maps each lane to a chain of
# blocks through an int32 [lanes, max_blocks] BLOCK TABLE: chain slot j of a
# lane covers absolute positions [j*block_size, (j+1)*block_size). Allocation,
# eviction, and sharing become per-block:
#
#   * a lane holds ceil(len/block_size) blocks, not max_len slots;
#   * blocks are REFCOUNTED, so a pinned/cached shared prefix maps read-only
#     into many lanes' tables at once (each new session skips that prefill
#     entirely) and copy-on-write splits a block only on the first divergent
#     write (SGLang's RadixAttention lesson, hash-chain flavored);
#   * attention gathers K/V through the table (ops.attention block-table
#     path), which is exact vs the dense layout: the gathered view is
#     position-contiguous, so slot index == absolute position and the same
#     causal/validity mask applies bit-for-bit.
#
# Device/host split: `PagedKVCache` is the jit-visible pytree (pools + the
# table as an operand — shapes static, so one compiled program serves any
# allocation state); `BlockPool` is the HOST-side allocator that owns the
# table mirror, refcounts, the free list, and the prefix index. Executors
# mutate the pool under their own bookkeeping lock and stamp a fresh table
# into the dispatch cache (a [lanes, max_blocks] int32 — trivial next to the
# step itself).
#
# Block 0 is a reserved SCRATCH block: unallocated table entries point at it,
# so in-graph writes from non-participating lanes (the co-batch garbage-step
# invariant) and reads past a lane's frontier land somewhere harmless — reads
# of it are always masked (slot >= valid length), writes to it are never
# attended.


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    """Jit-visible paged KV state: block pools + the lanes' block table.

    k/v: [L, num_blocks, block_size, Nkv, D] (block 0 = scratch);
    table: [lanes, max_blocks] int32 (chain slot j of lane b covers
    positions [j*bs, (j+1)*bs); unallocated entries = 0);
    length: int32 scalar, kept for interface parity with KVCache (lane
    executors track per-lane lengths host-side and ignore it).
    """

    k: jax.Array
    v: jax.Array
    table: jax.Array
    length: jax.Array

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def max_blocks(self) -> int:
        return self.table.shape[1]

    @property
    def max_len(self) -> int:
        """Per-lane positional capacity (the dense-equivalent max_len)."""
        return self.max_blocks * self.block_size

    @property
    def batch(self) -> int:
        return self.table.shape[0]

    @property
    def k_loc(self):
        """Paged storage is uniform-layout only (sliding-window models keep
        their dense rings on the classic path); None keeps the executors'
        `cache.k_loc is not None` ring checks working unchanged."""
        return None

    v_loc = k_loc

    @staticmethod
    def create(
        cfg: ModelConfig,
        num_layers: int,
        lanes: int,
        max_len: int,
        block_size: int = 32,
        num_blocks: Optional[int] = None,
        dtype=None,
    ) -> "PagedKVCache":
        dt = dtype or cfg.kv_jnp_dtype
        bs = int(block_size)
        mb = -(-int(max_len) // bs)  # ceil: blocks per lane chain
        nb = (lanes * mb + 1) if num_blocks is None else int(num_blocks)
        shape = (num_layers, nb, bs, cfg.num_kv_heads, cfg.head_dim)
        return PagedKVCache(
            k=jnp.zeros(shape, dt),
            v=jnp.zeros(shape, dt),
            table=jnp.zeros((lanes, mb), jnp.int32),
            length=jnp.int32(0),
        )


class _PrefixEntry:
    """One cached/pinned prefix block in the pool's prefix index. The index
    holds its OWN reference on the block (refcount +1), so the block
    survives the sessions that produced it and can be mapped into later
    lanes until evicted for space (pinned entries are never evicted).
    `ts` is the entry's last-touch time (index/registration/hit) on the
    pool's clock — an eviction's AGE (now - ts) is how long the entry sat
    cold before space pressure reclaimed it, the memory-plane telemetry's
    thrash-vs-working-set signal (obs: kv.prefix_evict_age_ms)."""

    __slots__ = ("block", "pinned", "ts")

    def __init__(self, block: int, pinned: bool = False, ts: float = 0.0):
        self.block = block
        self.pinned = pinned
        self.ts = ts


class BlockPool:
    """Host-side allocator for a PagedKVCache: free list, per-lane block
    chains, refcounts, copy-on-write, and the shared-prefix index.

    NOT thread-safe by itself — callers (the lane executors) mutate it
    under the same bookkeeping lock that guards their lane/session state.
    Device copies implied by CoW splits are returned as (src, dst) block
    pairs for the caller to apply under its device lock (`drain_copies`).
    """

    def __init__(
        self,
        cfg: ModelConfig,
        num_layers: int,
        lanes: int,
        max_len: int,
        block_size: int = 32,
        num_blocks: Optional[int] = None,
        dtype=None,
        clock: Optional[Callable[[], float]] = None,
    ):
        if cfg.sliding_window > 0:
            # rings already make sliding layers O(window); paging the
            # uniform layout under them would need a second table per
            # layer class — out of scope, and the capacity win lives in
            # the global layers anyway
            raise ValueError(
                "paged KV supports uniform-layout models only "
                "(sliding-window models keep the dense ring layout)"
            )
        self.cfg = cfg
        self.block_size = int(block_size)
        self.lanes = int(lanes)
        self.max_blocks = -(-int(max_len) // self.block_size)
        self.cache = PagedKVCache.create(
            cfg, num_layers, lanes, max_len, block_size=self.block_size,
            num_blocks=num_blocks, dtype=dtype,
        )
        self.num_blocks = self.cache.num_blocks
        if self.num_blocks < 2:
            raise ValueError("paged KV needs >= 2 blocks (block 0 is scratch)")
        # host mirrors (never read back from device)
        self.table = np.zeros((self.lanes, self.max_blocks), np.int32)
        self.refcount = np.zeros((self.num_blocks,), np.int32)
        self.refcount[0] = 1  # scratch block: never allocated, never freed
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self.lane_blocks = [0] * self.lanes  # chain length per lane
        self.lane_shared = [0] * self.lanes  # leading read-only blocks
        # prefix index: chained block-content key -> entry (LRU order)
        self._index: "OrderedDict[bytes, _PrefixEntry]" = OrderedDict()
        self._pending_copies: List[Tuple[int, int]] = []
        # effectiveness counters (surface in executor stats / gauges)
        self.cow_splits = 0
        self.prefix_hit_tokens = 0
        self.prefix_evictions = 0
        # entry-age clock + eviction observer: `on_evict(key, age_s)`
        # fires per reclaimed index entry with how long it sat since its
        # last touch (the executors wire it to a journal `prefix.evict`
        # event; failures are the HOOK's problem, never the allocator's)
        self.clock = clock if clock is not None else time.monotonic
        self.on_evict: Optional[Callable[[bytes, float], None]] = None

    # ------------------------------------------------------------ allocation

    def blocks_for(self, upto: int) -> int:
        return -(-int(upto) // self.block_size)

    def _alloc(self, owner: str) -> int:
        if not self._free:
            self._evict_cached(1)
        if not self._free:
            raise BufferError(
                f"KV block pool exhausted ({owner}): 0 free of "
                f"{self.num_blocks - 1} blocks "
                f"(block_size={self.block_size})"
            )
        b = self._free.pop()
        self.refcount[b] = 1
        return b

    def ensure(self, lane: int, upto: int, owner: str = "") -> None:
        """Grow `lane`'s chain with private blocks until it covers
        positions [0, upto). Raises BufferError carrying `owner` (the
        session/lane identity) when the pool cannot satisfy it."""
        need = self.blocks_for(upto)
        if need > self.max_blocks:
            raise BufferError(
                f"KV overflow ({owner}): {upto} > "
                f"{self.max_blocks * self.block_size}"
            )
        for j in range(self.lane_blocks[lane], need):
            self.table[lane, j] = self._alloc(owner)
            # advance incrementally: a mid-ensure exhaustion must leave
            # the blocks already claimed releasable, not leaked
            self.lane_blocks[lane] = j + 1

    def _decref(self, block: int) -> None:
        if block <= 0:
            return
        self.refcount[block] -= 1
        if self.refcount[block] == 0:
            self._free.append(block)

    def release_lane(self, lane: int) -> None:
        """Return a lane's chain to the pool (shared/cached blocks survive
        through their index references)."""
        for j in range(self.lane_blocks[lane]):
            self._decref(int(self.table[lane, j]))
        self.table[lane, :] = 0
        self.lane_blocks[lane] = 0
        self.lane_shared[lane] = 0

    # ------------------------------------------------------------ sharing

    def map_prefix(self, lane: int, keys: Sequence[bytes]) -> int:
        """Map the longest indexed run of `keys` into a FRESH lane's chain
        as read-only shared blocks; returns the number of tokens covered.
        The lane must be empty (admission calls this before any prefill)."""
        assert self.lane_blocks[lane] == 0
        m = 0
        for key in keys:
            ent = self._index.get(key)
            if ent is None:
                break
            self._index.move_to_end(key)
            ent.ts = self.clock()
            self.table[lane, m] = ent.block
            self.refcount[ent.block] += 1
            m += 1
        self.lane_blocks[lane] = m
        self.lane_shared[lane] = m
        covered = m * self.block_size
        self.prefix_hit_tokens += covered
        return covered

    def register_prefix(self, lane: int, keys: Sequence[bytes]) -> int:
        """Publish a lane's leading blocks into the prefix index under
        their content keys (after the lane's prefill wrote them). Blocks
        already indexed (the shared ones this lane mapped) are touched,
        not duplicated. Returns newly indexed block count."""
        added = 0
        for j, key in enumerate(keys):
            if j >= self.lane_blocks[lane]:
                break
            ent = self._index.get(key)
            if ent is not None:
                self._index.move_to_end(key)
                ent.ts = self.clock()
                continue
            block = int(self.table[lane, j])
            if block <= 0 or j < self.lane_shared[lane]:
                continue
            self._index[key] = _PrefixEntry(block, ts=self.clock())
            self.refcount[block] += 1  # the index's own reference
            added += 1
        return added

    def pin(self, keys: Sequence[bytes]) -> int:
        """Mark indexed entries pinned (never evicted for space); returns
        how many of `keys` were found."""
        n = 0
        for key in keys:
            ent = self._index.get(key)
            if ent is not None:
                ent.pinned = True
                n += 1
        return n

    def unpin(self, keys: Sequence[bytes]) -> None:
        for key in keys:
            ent = self._index.get(key)
            if ent is not None:
                ent.pinned = False

    def _evict_cached(self, need: int) -> None:
        """Drop LRU unpinned index entries whose block is otherwise unused
        (refcount 1 == only the index holds it) until `need` blocks are
        free. Entries still mapped into live lanes are skipped — their
        blocks could not be reclaimed anyway."""
        if need <= len(self._free):
            return
        for key in list(self._index):
            ent = self._index[key]
            if ent.pinned or self.refcount[ent.block] != 1:
                continue
            del self._index[key]
            self._decref(ent.block)
            self.prefix_evictions += 1
            if self.on_evict is not None:
                try:
                    self.on_evict(key, max(0.0, self.clock() - ent.ts))
                except Exception:
                    pass  # telemetry must never fail an allocation
            if len(self._free) >= need:
                return

    # ------------------------------------------------------------ CoW

    def make_writable(self, lane: int, from_pos: int, owner: str = "") -> None:
        """Copy-on-write split every MULTIPLY-REFERENCED block of `lane`
        covering positions >= from_pos (the first divergent write): each
        gets a private copy, the table repoints, and the (src, dst)
        device copy is queued for `drain_copies`.

        The writable test is the REFCOUNT, not just the mapped-prefix
        prefix (`lane_shared`): a lane that PUBLISHED its own blocks
        (register_prefix) or was fork_lane'd FROM holds blocks the index
        / a child still reads at refcount >= 2 with lane_shared
        untouched — an in-place rollback rewrite there would silently
        corrupt every future sharer. A block whose only extra reference
        is a pending copy gets split too (conservative, rare, correct).
        The common decode case (private frontier) costs one refcount
        compare per chain block past from_pos."""
        first = int(from_pos) // self.block_size
        for j in range(first, self.lane_blocks[lane]):
            old = int(self.table[lane, j])
            if old <= 0 or self.refcount[old] <= 1:
                continue
            new = self._alloc(owner)
            self._queue_copy(old, new)
            self.table[lane, j] = new
            self._decref(old)
            self.cow_splits += 1
        self.lane_shared[lane] = min(self.lane_shared[lane], first)

    def _queue_copy(self, src: int, dst: int) -> None:
        """Queue a device block copy. The queue holds its OWN reference on
        `src` (released at drain) so a teardown/restart freeing the source
        lane between queue and apply cannot recycle the block under the
        pending copy."""
        self.refcount[src] += 1
        self._pending_copies.append((src, dst))

    def fork_lane(
        self, src: int, dst: int, prefix_len: int, owner: str = ""
    ) -> None:
        """Seed FRESH lane `dst` with lane `src`'s first `prefix_len`
        positions: full blocks map read-only (refcounted, CoW on later
        divergence); a partial tail block gets a private copy (queued for
        drain_copies). The block-pool flavor of the dense executors'
        fork_session device copy."""
        assert self.lane_blocks[dst] == 0
        full = int(prefix_len) // self.block_size
        for j in range(full):
            b = int(self.table[src, j])
            self.table[dst, j] = b
            self.refcount[b] += 1
        self.lane_shared[dst] = full
        self.lane_blocks[dst] = full
        if prefix_len % self.block_size:
            nb = self._alloc(owner)
            self._queue_copy(int(self.table[src, full]), nb)
            self.table[dst, full] = nb
            self.lane_blocks[dst] = full + 1

    def drain_copies(self) -> List[Tuple[int, int]]:
        """Take the queued CoW (src, dst) block copies; the caller applies
        them on device (under its device lock) BEFORE the next dispatch
        that reads the split lane. Releases the queue's source references
        — a source freed here may be recycled by a LATER allocation, but
        device content only changes in dispatches, which the caller
        serializes after the copy."""
        out, self._pending_copies = self._pending_copies, []
        for src, _dst in out:
            self._decref(src)
        return out

    # ------------------------------------------------------------ dispatch

    def device_table(self, max_blocks: Optional[int] = None):
        """Fresh device table from the host mirror — stamp into the
        dispatch cache (executors: dataclasses.replace(cache, table=...)).
        `max_blocks` (chain_clamp) narrows the stamped width so dispatches
        gather/walk only slots some lane can actually reach."""
        if max_blocks is None:
            return jnp.asarray(self.table)
        return jnp.asarray(self.table[:, :max_blocks])

    def chain_clamp(self) -> int:
        """Power-of-two bucket of the window's MAXIMUM allocated chain
        length (>= 1, capped at the full table width). Stamping tables at
        this width (sync_paged) keeps short sessions co-batched with long
        ones from gathering — and masking — scratch-block slots nobody
        can attend to: the XLA fallback's gather_block_kv materializes
        O(width * bs) per layer per step, so width is the bandwidth term.
        Bucketed so jit retraces per power-of-two growth step, the same
        coarseness every other bucketed dispatch shape uses. Blocks are
        allocated BEFORE the dispatch that writes them (ensure), so every
        lane's write frontier sits inside its allocated chain and the
        clamp can never cut off a real read or write."""
        used = max(self.lane_blocks) if self.lane_blocks else 0
        bucket = 1
        while bucket < used:
            bucket <<= 1
        return min(bucket, self.max_blocks)

    # ------------------------------------------------------------ gauges

    @property
    def blocks_used(self) -> int:
        return self.num_blocks - 1 - len(self._free)

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    @property
    def cow_shared(self) -> int:
        """Blocks currently mapped by more than one holder (lanes and/or
        the prefix index) — the dedupe the pool is earning its keep with."""
        return int(np.sum(self.refcount[1:] >= 2))

    @property
    def pins_resident(self) -> int:
        return sum(1 for e in self._index.values() if e.pinned)

    def digest_keys(self, limit: int = 0) -> List[bytes]:
        """Size-bounded selection of indexed prefix keys for the gossiped
        digest (core.prefix.make_digest): PINNED entries first (they are
        resident by contract — the strongest affinity promise a replica
        can gossip), then most-recently-touched cache entries until
        `limit`. Keys are chained, so any included key identifies its
        whole prefix; MRU ordering makes the digest track the HOT working
        set when the index outgrows the budget."""
        from inferd_tpu.core import prefix as prefixlib

        if limit <= 0:
            limit = prefixlib.DIGEST_MAX_KEYS
        out: List[bytes] = [
            k for k, e in self._index.items() if e.pinned
        ][:limit]
        if len(out) < limit:
            seen = set(out)
            for k in reversed(self._index):  # MRU first
                if k in seen:
                    continue
                out.append(k)
                if len(out) >= limit:
                    break
        return out

    def block_stats(self) -> Dict[str, Any]:
        return {
            "block_size": self.block_size,
            "blocks_total": self.num_blocks - 1,
            "blocks_used": self.blocks_used,
            "blocks_free": self.blocks_free,
            "cow_shared": self.cow_shared,
            "cow_splits": self.cow_splits,
            "prefix_entries": len(self._index),
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_evictions": self.prefix_evictions,
            "pins_resident": self.pins_resident,
        }


def paged_copy_blocks(cache: PagedKVCache, pairs: List[Tuple[int, int]],
                      copy_fn: Callable) -> PagedKVCache:
    """Apply queued CoW block copies on device via `copy_fn` (a jitted
    (cache, src [n], dst [n]) -> cache with the cache donated). Groups all
    pairs into one call; `n` varies rarely (CoW splits are admission-time
    events), so the compile set stays small."""
    if not pairs:
        return cache
    src = jnp.asarray([p[0] for p in pairs], jnp.int32)
    dst = jnp.asarray([p[1] for p in pairs], jnp.int32)
    return copy_fn(cache, src, dst)


def sync_paged(pool: BlockPool, cache: PagedKVCache, copy_fn: Callable,
               mu) -> PagedKVCache:
    """Dispatch-ready paged cache: apply queued CoW block copies and
    stamp the CURRENT block table (the host mirror moved since the last
    dispatch — allocations, prefix maps, splits). The ONE implementation
    behind both lane executors' `_sync_paged` (a drifted copy here would
    be a correctness bug, not a style problem). Call under the caller's
    DEVICE lock with `mu` (its bookkeeping lock) NOT held; the caller
    must rebind its cache reference to the return value (the copy jit
    donates)."""
    with mu:
        pairs = pool.drain_copies()
        # chain-length clamp: stamp only the (bucketed) max allocated
        # chain width, so the paged read path — XLA gather_block_kv and
        # the Pallas chain-walk kernel alike — does O(longest chain) work
        # per lane instead of O(full table width)
        table = pool.device_table(pool.chain_clamp())
    if pairs:
        cache = paged_copy_blocks(cache, pairs, copy_fn)
    return dataclasses.replace(cache, table=table)


def grow(cache: KVCache, new_max_len: int) -> KVCache:
    """Host-side reallocation to a larger bucket (copies populated slots).

    Used by the session registry when a session outgrows its bucket; pairs
    with bucketed jit shapes so growth is rare and amortized. Ring buffers
    are fixed-size by construction and carry over untouched.
    """
    if new_max_len <= cache.max_len:
        return cache
    l, b, t, n, d = cache.k.shape
    pad = [(0, 0), (0, 0), (0, new_max_len - t), (0, 0), (0, 0)]
    return KVCache(
        k=jnp.pad(cache.k, pad), v=jnp.pad(cache.v, pad), length=cache.length,
        k_loc=cache.k_loc, v_loc=cache.v_loc,
    )
