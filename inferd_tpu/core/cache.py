"""Functional per-session KV cache.

Replaces the reference's server-side mutable `DynamicCache` keyed by session
id (/root/reference/models/qwen3/server/qwen3_server_module.py:220,253) with
an explicit, preallocated, fixed-shape buffer threaded through jitted calls —
the TPU-idiomatic design: XLA sees one static shape per (batch, max_len)
bucket instead of a shape that grows every token (which would trigger a
recompile per step).

Layout: k/v are [num_global_layers, batch, max_len, num_kv_heads, head_dim];
`length` is the number of populated positions. Overflow is checked host-side
(`ensure_room`) because in-jit dynamic_update_slice clamps silently (see
models/qwen3.decoder_layer contract).

Sliding-window models (Gemma-2, GPT-OSS) additionally carry RING buffers
`k_loc`/`v_loc` [num_sliding_layers, batch, ring, kv, d] for their sliding
(even-global-index) layers: a sliding layer never attends past its window,
so its storage is O(window), not O(context) — position p lives at slot
p % ring until position p + ring overwrites it. `ring = round16(window) +
RING_MARGIN`; the margin is what makes speculative rollback and bounded
fork-truncation safe (models/qwen3._ring_attend_update documents the
aliasing invariant). For non-sliding models `k_loc`/`v_loc` are None and
the layout is exactly the classic single-buffer one.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from inferd_tpu.config import ModelConfig

# Extra ring slots past the (16-rounded) window. Bounds how far "newer"
# data may sit in a slot whose formula position is already inside some
# window: speculative rollback depth and fork truncation depth must both
# stay under this margin (enforced at those call sites).
RING_MARGIN = 64


def ring_slots(cfg: ModelConfig) -> int:
    """Ring length for sliding layers: 16-rounded window + safety margin."""
    return (int(cfg.sliding_window) + 15) // 16 * 16 + RING_MARGIN


def sliding_layer_ids(
    cfg: ModelConfig, num_layers: int, layer_offset: int
) -> List[int]:
    """Stack-local indices of the SLIDING layers (static python): global
    layer index (layer_offset + i) even — the Gemma-2/GPT-OSS alternation
    (models/qwen3.layer_windows)."""
    if not cfg.sliding_window:
        return []
    return [i for i in range(num_layers) if (layer_offset + i) % 2 == 0]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: jax.Array  # [Lg, B, T, Nkv, D] global (full-length) layers
    v: jax.Array  # [Lg, B, T, Nkv, D]
    length: jax.Array  # int32 scalar: populated positions
    k_loc: Optional[jax.Array] = None  # [Ll, B, R, Nkv, D] sliding-layer rings
    v_loc: Optional[jax.Array] = None

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def batch(self) -> int:
        return self.k.shape[1]

    @property
    def ring(self) -> Optional[int]:
        return None if self.k_loc is None else self.k_loc.shape[2]

    @staticmethod
    def create(
        cfg: ModelConfig,
        num_layers: int,
        batch: int,
        max_len: int,
        dtype=None,
        layer_offset: int = 0,
        ring: Optional[bool] = None,
    ) -> "KVCache":
        """ring=None auto-enables ring storage for sliding-window configs;
        ring=False forces the classic uniform full-length layout (the
        comparison/compat path — also what executors with a TRACED layer
        offset must use)."""
        dt = dtype or cfg.kv_jnp_dtype
        use_ring = cfg.sliding_window > 0 if ring is None else (
            ring and cfg.sliding_window > 0
        )
        loc = sliding_layer_ids(cfg, num_layers, layer_offset) if use_ring else []
        if not loc:  # uniform layout (forced, no window, or global-only slice)
            shape = (num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
            return KVCache(
                k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt), length=jnp.int32(0)
            )
        lg = num_layers - len(loc)
        r = ring_slots(cfg)
        gshape = (lg, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
        lshape = (len(loc), batch, r, cfg.num_kv_heads, cfg.head_dim)
        return KVCache(
            k=jnp.zeros(gshape, dt),
            v=jnp.zeros(gshape, dt),
            length=jnp.int32(0),
            k_loc=jnp.zeros(lshape, dt),
            v_loc=jnp.zeros(lshape, dt),
        )

    def ensure_room(self, new_tokens: int) -> None:
        """Host-side overflow guard — call before dispatching a jitted step.
        Rings never overflow (they wrap); the global buffers bound growth."""
        used = int(self.length)
        if used + new_tokens > self.max_len:
            raise BufferError(
                f"KV cache overflow: {used} used + {new_tokens} new > {self.max_len}"
            )

    def updated(self, k: jax.Array, v: jax.Array, new_tokens) -> "KVCache":
        """New cache with written buffers and advanced length (pure)."""
        return KVCache(
            k=k, v=v, length=self.length + new_tokens,
            k_loc=self.k_loc, v_loc=self.v_loc,
        )


def lane_slice(cache: KVCache, lane) -> KVCache:
    """One lane's KVCache view, [.., 1, ..] on the batch axis (global +
    ring buffers). Shared by the lane-indexed engines (core.batch prefill,
    core.spec_batch draft prefill) so the ring-buffer field handling lives
    in exactly one place."""
    sl = lambda a: jax.lax.dynamic_slice_in_dim(a, lane, 1, axis=1)
    return KVCache(
        k=sl(cache.k), v=sl(cache.v), length=cache.length,
        k_loc=None if cache.k_loc is None else sl(cache.k_loc),
        v_loc=None if cache.v_loc is None else sl(cache.v_loc),
    )


def lane_write(cache: KVCache, lane, nc: KVCache) -> KVCache:
    """Write a lane_slice-shaped cache back into `lane` (inverse of
    lane_slice; in-place under donation)."""
    up = lambda a, b: jax.lax.dynamic_update_slice_in_dim(a, b, lane, axis=1)
    return KVCache(
        k=up(cache.k, nc.k), v=up(cache.v, nc.v), length=cache.length,
        k_loc=None if cache.k_loc is None else up(cache.k_loc, nc.k_loc),
        v_loc=None if cache.v_loc is None else up(cache.v_loc, nc.v_loc),
    )


def grow(cache: KVCache, new_max_len: int) -> KVCache:
    """Host-side reallocation to a larger bucket (copies populated slots).

    Used by the session registry when a session outgrows its bucket; pairs
    with bucketed jit shapes so growth is rare and amortized. Ring buffers
    are fixed-size by construction and carry over untouched.
    """
    if new_max_len <= cache.max_len:
        return cache
    l, b, t, n, d = cache.k.shape
    pad = [(0, 0), (0, 0), (0, new_max_len - t), (0, 0), (0, 0)]
    return KVCache(
        k=jnp.pad(cache.k, pad), v=jnp.pad(cache.v, pad), length=cache.length,
        k_loc=cache.k_loc, v_loc=cache.v_loc,
    )
