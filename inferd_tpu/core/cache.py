"""Functional per-session KV cache.

Replaces the reference's server-side mutable `DynamicCache` keyed by session
id (/root/reference/models/qwen3/server/qwen3_server_module.py:220,253) with
an explicit, preallocated, fixed-shape buffer threaded through jitted calls —
the TPU-idiomatic design: XLA sees one static shape per (batch, max_len)
bucket instead of a shape that grows every token (which would trigger a
recompile per step).

Layout: k/v are [num_layers, batch, max_len, num_kv_heads, head_dim];
`length` is the number of populated slots. Overflow is checked host-side
(`ensure_room`) because in-jit dynamic_update_slice clamps silently (see
models/qwen3.decoder_layer contract).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from inferd_tpu.config import ModelConfig


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: jax.Array  # [L, B, T, Nkv, D]
    v: jax.Array  # [L, B, T, Nkv, D]
    length: jax.Array  # int32 scalar: populated slots

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def batch(self) -> int:
        return self.k.shape[1]

    @staticmethod
    def create(
        cfg: ModelConfig,
        num_layers: int,
        batch: int,
        max_len: int,
        dtype=None,
    ) -> "KVCache":
        shape = (num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
        dt = dtype or cfg.kv_jnp_dtype
        return KVCache(
            k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt), length=jnp.int32(0)
        )

    def ensure_room(self, new_tokens: int) -> None:
        """Host-side overflow guard — call before dispatching a jitted step."""
        used = int(self.length)
        if used + new_tokens > self.max_len:
            raise BufferError(
                f"KV cache overflow: {used} used + {new_tokens} new > {self.max_len}"
            )

    def updated(self, k: jax.Array, v: jax.Array, new_tokens) -> "KVCache":
        """New cache with written buffers and advanced length (pure)."""
        return KVCache(k=k, v=v, length=self.length + new_tokens)


def grow(cache: KVCache, new_max_len: int) -> KVCache:
    """Host-side reallocation to a larger bucket (copies populated slots).

    Used by the session registry when a session outgrows its bucket; pairs
    with bucketed jit shapes so growth is rare and amortized.
    """
    if new_max_len <= cache.max_len:
        return cache
    l, b, t, n, d = cache.k.shape
    pad = [(0, 0), (0, 0), (0, new_max_len - t), (0, 0), (0, 0)]
    return KVCache(
        k=jnp.pad(cache.k, pad), v=jnp.pad(cache.v, pad), length=cache.length
    )
