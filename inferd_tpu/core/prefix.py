"""Prefix-matching shared by the engine's pin store and the network
clients' pin registry (one definition of "which pinned prefix applies").

Deliberately JAX-free: client.base imports this and must never initialize
a backend (a client machine shouldn't claim a TPU to match tuples).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: Bytes of the 16-byte chained block key that ride in a gossiped prefix
#: digest (hex-encoded). 4 bytes keep a whole digest ~tens of bytes on
#: the wire while a spurious per-key collision stays ~2^-32 — harmless
#: for a bounded routing BONUS (a false hit costs a slightly-suboptimal
#: pick, never correctness: the landing replica just prefills normally).
DIGEST_KEY_BYTES = 4

#: Cap on prompt blocks a probe digests: an entry router must not hash a
#: 100k-token prompt per routing decision. 64 blocks x 32-token default
#: block size = 2048 leading prompt tokens of affinity reach.
DIGEST_MAX_KEYS = 64

#: Cap on digest entries a replica GOSSIPS — tighter than the probe cap
#: because the record rides every gossip frame and frames grow O(fleet)
#: (PR 12's UDP-datagram concern): 32 keys x 8 hex chars ~ 300 wire
#: bytes per paged replica.
DIGEST_GOSSIP_KEYS = 32


def normalize_ids(ids: Sequence[int]) -> Tuple[int, ...]:
    out = tuple(int(t) for t in ids)
    if not out:
        raise ValueError("prefix ids must be non-empty")
    return out


def block_keys(ids: Sequence[int], block_size: int,
               n_blocks: Optional[int] = None,
               salt: Optional[str] = None) -> List[bytes]:
    """Chained content keys for the FULL blocks of a token stream — the
    paged KV pool's shared-prefix identity (core.cache.BlockPool).

    Key j digests block j's token ids AND every preceding block's key
    (a cumulative blake2b chain), so equal keys mean equal ENTIRE
    prefixes, not just equal block contents — two prompts sharing block
    key j share KV for positions [0, (j+1)*block_size) exactly. Only
    complete blocks get keys: a partial tail block's KV depends on
    tokens that may still diverge.

    `salt` scopes the chain to a serving identity BEYOND the tokens:
    a multi-tenant adapter session's KV depends on its adapter weights,
    so its keys are salted with the adapter name — two tenants sharing
    a prompt must never share KV blocks, while one tenant's sessions
    still do. Empty/None salt leaves the chain byte-identical to the
    pre-salt format (the kill-switch contract)."""
    full = len(ids) // block_size
    if n_blocks is not None:
        full = min(full, n_blocks)
    h = hashlib.blake2b(digest_size=16)
    h.update(str(block_size).encode())
    if salt:
        h.update(b"\x00" + str(salt).encode())
    keys: List[bytes] = []
    for j in range(full):
        block = ids[j * block_size:(j + 1) * block_size]
        h.update(b"".join(int(t).to_bytes(8, "little", signed=True)
                          for t in block))
        keys.append(h.digest())
    return keys


def digest_key(key: bytes) -> str:
    """Truncated wire form of one chained block key (block_keys output):
    the ONE definition shared by the pool's gossiped digest
    (core.cache.BlockPool.digest_keys) and the routing probe below, so
    producer and matcher can never truncate differently."""
    return key[:DIGEST_KEY_BYTES].hex()


def make_digest(keys: Sequence[bytes], block_size: int) -> Dict[str, Any]:
    """Gossip-ready prefix digest: {"bs": block size, "k": [truncated
    keys]} — the `pfx` record field (runtime/node.announce). `bs` rides
    along because the chained keys are block-size-scoped: a probe must
    re-derive the prompt's keys at EACH candidate's block size or equal
    prefixes would never match across configs. Size-bounded at
    DIGEST_MAX_KEYS entries (callers pick which keys matter)."""
    return {
        "bs": int(block_size),
        "k": [digest_key(k) for k in keys[:DIGEST_MAX_KEYS]],
    }


class AffinityProbe:
    """One prompt's cache-affinity matcher against gossiped digests.

    Built ONCE per routing decision from the prompt ids; `depth_frac`
    then scores any candidate's gossip record in O(digest) set lookups:
    the fraction of the prompt's (capped) full blocks whose chained key
    the candidate advertises, 0.0..1.0. Keys are chained (equal key ==
    equal ENTIRE prefix), so the DEEPEST matching key alone names the
    shared coverage. Per-block-size key chains are derived lazily and
    memoized — a fleet gossiping one block size hashes the prompt once,
    whatever the candidate count.

    `salt` MUST carry the session's serving identity beyond the tokens
    (a multi-tenant adapter session passes its adapter name — the same
    salt its KV chains register under, see block_keys): an unsalted
    probe for tenant traffic both MISSES the tenant's actually-cached
    blocks and FALSE-matches base-session digests for the same prompt,
    bonusing a replica whose blocks the session cannot map."""

    def __init__(self, prompt_ids: Sequence[int],
                 max_keys: int = DIGEST_MAX_KEYS,
                 salt: Optional[str] = None):
        self.prompt_ids = [int(t) for t in prompt_ids]
        self.max_keys = int(max_keys)
        self.salt = None if salt is None else str(salt)
        self._by_bs: Dict[int, List[str]] = {}

    def keys_for(self, block_size: int) -> List[str]:
        bs = int(block_size)
        if bs <= 0:
            return []
        cached = self._by_bs.get(bs)
        if cached is None:
            cached = [
                digest_key(k) for k in block_keys(
                    self.prompt_ids, bs, n_blocks=self.max_keys,
                    salt=self.salt,
                )
            ]
            self._by_bs[bs] = cached
        return cached

    def depth_frac(self, record: Dict[str, Any]) -> float:
        """Matched-prefix depth against one gossip record's `pfx` digest
        as a fraction of the prompt's digestible blocks (0.0 when the
        record has no digest, a malformed one, or no matching key).
        Bounded by construction — the routing bonus scales off this."""
        pfx = record.get("pfx")
        if not isinstance(pfx, dict):
            return 0.0
        try:
            bs = int(pfx.get("bs", 0))
        except (TypeError, ValueError):
            return 0.0
        held = pfx.get("k")
        if bs <= 0 or not isinstance(held, (list, tuple)) or not held:
            return 0.0
        keys = self.keys_for(bs)
        if not keys:
            return 0.0
        held_set = {k for k in held if isinstance(k, str)}
        depth = 0
        for j, key in enumerate(keys):
            if key in held_set:
                depth = j + 1
        return depth / len(keys)


def longest_prefix_match(
    keys: Iterable[Tuple[int, ...]], prompt_ids: Sequence[int]
) -> Optional[Tuple[int, ...]]:
    """Longest key that `prompt_ids` starts with, or None."""
    best: Optional[Tuple[int, ...]] = None
    prompt = tuple(prompt_ids)
    for ids in keys:
        if len(ids) <= len(prompt) and prompt[: len(ids)] == ids:
            if best is None or len(ids) > len(best):
                best = ids
    return best
