"""Prefix-matching shared by the engine's pin store and the network
clients' pin registry (one definition of "which pinned prefix applies").

Deliberately JAX-free: client.base imports this and must never initialize
a backend (a client machine shouldn't claim a TPU to match tuples).
"""

from __future__ import annotations

import hashlib
from typing import Iterable, List, Optional, Sequence, Tuple


def normalize_ids(ids: Sequence[int]) -> Tuple[int, ...]:
    out = tuple(int(t) for t in ids)
    if not out:
        raise ValueError("prefix ids must be non-empty")
    return out


def block_keys(ids: Sequence[int], block_size: int,
               n_blocks: Optional[int] = None) -> List[bytes]:
    """Chained content keys for the FULL blocks of a token stream — the
    paged KV pool's shared-prefix identity (core.cache.BlockPool).

    Key j digests block j's token ids AND every preceding block's key
    (a cumulative blake2b chain), so equal keys mean equal ENTIRE
    prefixes, not just equal block contents — two prompts sharing block
    key j share KV for positions [0, (j+1)*block_size) exactly. Only
    complete blocks get keys: a partial tail block's KV depends on
    tokens that may still diverge."""
    full = len(ids) // block_size
    if n_blocks is not None:
        full = min(full, n_blocks)
    h = hashlib.blake2b(digest_size=16)
    h.update(str(block_size).encode())
    keys: List[bytes] = []
    for j in range(full):
        block = ids[j * block_size:(j + 1) * block_size]
        h.update(b"".join(int(t).to_bytes(8, "little", signed=True)
                          for t in block))
        keys.append(h.digest())
    return keys


def longest_prefix_match(
    keys: Iterable[Tuple[int, ...]], prompt_ids: Sequence[int]
) -> Optional[Tuple[int, ...]]:
    """Longest key that `prompt_ids` starts with, or None."""
    best: Optional[Tuple[int, ...]] = None
    prompt = tuple(prompt_ids)
    for ids in keys:
        if len(ids) <= len(prompt) and prompt[: len(ids)] == ids:
            if best is None or len(ids) > len(best):
                best = ids
    return best
