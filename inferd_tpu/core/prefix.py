"""Prefix-matching shared by the engine's pin store and the network
clients' pin registry (one definition of "which pinned prefix applies").

Deliberately JAX-free: client.base imports this and must never initialize
a backend (a client machine shouldn't claim a TPU to match tuples).
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple


def normalize_ids(ids: Sequence[int]) -> Tuple[int, ...]:
    out = tuple(int(t) for t in ids)
    if not out:
        raise ValueError("prefix ids must be non-empty")
    return out


def longest_prefix_match(
    keys: Iterable[Tuple[int, ...]], prompt_ids: Sequence[int]
) -> Optional[Tuple[int, ...]]:
    """Longest key that `prompt_ids` starts with, or None."""
    best: Optional[Tuple[int, ...]] = None
    prompt = tuple(prompt_ids)
    for ids in keys:
        if len(ids) <= len(prompt) and prompt[: len(ids)] == ids:
            if best is None or len(ids) > len(best):
                best = ids
    return best
