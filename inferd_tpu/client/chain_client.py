"""Chain generation client: the client drives a FIXED chain of stage servers.

Capability parity with the reference's gRPC slice — `RPCQwen3Client`
(/root/reference/models/qwen3/client/rpc_client.py:36-57: one stub per
server in fixed order, hidden states re-fed hop to hop) and the generation
loop of `Qwen3Client.generate` (/root/reference/models/qwen3/client/
client.py:204-287: prefill, then one token per step, per-session KV living
server-side, client-side sampling) — redesigned:

  * hub-and-spoke over the SAME node endpoint as the swarm path (`/forward`
    with `relay: false`) — one unified node runtime serves both topologies,
    where the reference had two disjoint server stacks;
  * the wire carries (tokens | hidden, start_pos) only — RoPE cos/sin and
    the causal mask are computed inside each stage from absolute positions
    (the reference shipped 5 pickled tensors per hop, rpc_client.py:47-54);
  * no model weights on the client: stage 0 embeds, the last stage returns
    last-token logits (the reference client held embed_tokens/norm/lm_head
    and shipped full hidden states both ways every step).

The chain is positional: `server_addrs[i]` serves stage i. For dynamic
routing, load balancing, and failover, use SwarmClient instead — ChainClient
is the minimal fixed-topology deployment (no DHT required). The outer
generation loop is shared with SwarmClient via client.base.GenerationClient.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from inferd_tpu.client.base import GenerationClient
from inferd_tpu.config import SamplingConfig
from inferd_tpu.core.tokenizer import Tokenizer

log = logging.getLogger(__name__)


class ChainClient(GenerationClient):
    """Drives each stage server in fixed order, carrying activations.

    `timeout_s` is the per-hop budget; the default leaves room for the first
    request's server-side XLA compile of the stage forward (the reference's
    30 s gRPC deadline, rpc_client.py:44, is too short for a cold jit).
    """

    def __init__(
        self,
        server_addrs: Sequence[Tuple[str, int]],  # [(host, port)] per stage, in order
        sampling: Optional[SamplingConfig] = None,
        tokenizer: Optional[Tokenizer] = None,
        timeout_s: float = 300.0,
        prefill_chunk: int = 512,
    ):
        if not server_addrs:
            raise ValueError("need at least one stage server address")
        super().__init__(sampling, tokenizer, timeout_s, prefill_chunk)
        self.server_addrs = [tuple(a) for a in server_addrs]

    async def _post(self, addr: Tuple[str, int], path: str, body: Dict[str, Any]) -> Dict[str, Any]:
        host, port = addr
        return await self._post_url(f"http://{host}:{port}{path}", body)

    async def _forward_through_chain(
        self, session_id: str, tokens: List[int], start_pos: int
    ) -> np.ndarray:
        """One pipeline pass, client-carried: tokens -> ... -> last-token
        logits (reference forward_through_chain, rpc_client.py:36-57)."""
        from inferd_tpu.client.base import deadline_wire
        from inferd_tpu.obs import trace as tracelib

        payload: Dict[str, Any] = {
            "tokens": np.asarray([tokens], dtype=np.int32),
            "start_pos": start_pos,
            "real_len": len(tokens),
        }
        for stage, addr in enumerate(self.server_addrs):
            # per-hop wire span: the client drives every stage itself, so
            # each hop gets its own send/recv anchor pair; the envelope
            # `trace` key (omitted when tracing is off) parents the
            # server-side spans to this hop; `deadline_ms` rides the same
            # conditional way (every hub-and-spoke hop re-derives the
            # remaining budget from the SAME absolute deadline)
            with self.tracer.span("hop", "wire", attrs={"stage": stage}):
                env = tracelib.attach_wire({
                    "task_id": str(uuid.uuid4()),
                    "session_id": session_id,
                    "stage": stage,
                    "relay": False,
                    "payload": payload,
                    **deadline_wire(),
                })
                resp = await self._post(addr, "/forward", env)
            result = resp["result"]
            if "logits" in result:
                return np.asarray(result["logits"])[0]
            payload = {
                "hidden": result["hidden"],
                "start_pos": int(result.get("start_pos", start_pos)),
                "real_len": int(result.get("real_len", len(tokens))),
            }
        raise RuntimeError("last stage returned no logits — is the chain complete?")

    # -- GenerationClient transport interface --------------------------------

    async def _step(
        self, session_id: str, tokens: List[int], start_pos: int
    ) -> np.ndarray:
        return await self._forward_through_chain(session_id, tokens, start_pos)

    async def _end_session(self, session_id: str) -> None:
        """Drop the session's KV on every stage server, concurrently — one
        dead server must not stall cleanup for the others."""
        async def one(stage: int, addr: Tuple[str, int]) -> None:
            await self._post(
                addr,
                "/end_session",
                {"session_id": session_id, "stage": stage, "relay": False},
            )

        await asyncio.gather(
            *(one(s, a) for s, a in enumerate(self.server_addrs)),
            return_exceptions=True,  # best effort: servers TTL-sweep orphans
        )

    async def _fork_session(
        self, new_session_id: str, parent_session_id: str, prefix_len: int
    ) -> bool:
        """Fork the parent's KV prefix on EVERY stage server (hub-and-spoke:
        the client addresses each stage directly). All stages must succeed —
        a partial fork reports False and the caller cleans up + re-prefills."""
        async def one(stage: int, addr: Tuple[str, int]):
            return await self._post(
                addr,
                "/fork_session",
                {
                    "session_id": new_session_id,
                    "parent_session_id": parent_session_id,
                    "prefix_len": prefix_len,
                    "stage": stage,
                    "relay": False,
                },
            )

        results = await asyncio.gather(
            *(one(s, a) for s, a in enumerate(self.server_addrs)),
            return_exceptions=True,
        )
        # a clean ok=False means the parent is truly gone there (the caller
        # drops the pin); a transport exception means the parent may be fine
        # — re-raise so the caller keeps the pin and just re-prefills
        if any(isinstance(r, dict) and not r.get("ok") for r in results):
            return False
        for r in results:
            if isinstance(r, BaseException):
                raise r
        return True

    # kept public: tests and operators end sessions explicitly
    async def end_session(self, session_id: str) -> None:
        await self._end_session(session_id)
