"""Shared generation-client front end.

Both client topologies — SwarmClient (relay: enter at stage 0, the swarm
routes hop-to-hop, reference petals/send_message.py:27-60) and ChainClient
(hub-and-spoke: the client drives each stage, reference models/qwen3/client/
client.py:204-287) — run the exact same outer loop: tokenize, prefill, then
sample-append-step until EOS/budget, then drop the session's server-side KV.
That loop lives here once; subclasses provide only the transport step.
"""

from __future__ import annotations

import asyncio
import contextvars
import random
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence

import aiohttp
import numpy as np
from aiohttp import ClientSession, ClientTimeout

from inferd_tpu.config import SamplingConfig
from inferd_tpu.core import prefix as prefixlib
from inferd_tpu.core.tokenizer import Tokenizer
from inferd_tpu.obs import trace as tracelib
from inferd_tpu.runtime import wire
from inferd_tpu.utils import retry as retrylib


class ServerError(RuntimeError):
    """Non-200 wire response. `code` is the node's machine-readable error
    class (runtime.node error codes); `retryable` says whether restarting
    the generation under a fresh session can possibly help; `retry_after`
    (seconds, optional) is the node's busy-503 pacing hint — the retry
    loop waits at least this long instead of hammering a shedding node."""

    def __init__(
        self, message: str, status: int, code: Optional[str] = None,
        retry_after: Optional[float] = None,
        resume_from: Optional[int] = None,
    ):
        super().__init__(message)
        self.status = status
        self.code = code
        self.retry_after = retry_after
        # standby promotion offer (crash-tolerant sessions): a
        # session_state 409 carrying the replicated-KV frontier — the
        # generation loop re-sends only the tokens past it (bounded
        # re-prefill) instead of restarting the whole session
        self.resume_from = resume_from

    @property
    def retryable(self) -> bool:
        # 5xx: transient node-side trouble (compute crash, dead next hop, no
        # server for a stage yet — adoption may fix it). "session_state":
        # this session's KV is gone/out-of-order on the serving replica
        # (e.g. it died and a fresh one answered) — a new session rebuilds
        # it. Everything else (wrong_stage topology errors, KV overflow,
        # malformed requests, an expired end-to-end deadline) is
        # deterministic for this request: retrying cannot succeed.
        return self.status >= 500 or self.code == "session_state"


# end-to-end deadline of the generation currently running in THIS asyncio
# task (set by generate_ids when the caller passes deadline_s). A
# contextvar — not a client attribute — so concurrent generations on one
# shared client each carry their own budget. Transports read it via
# deadline_wire() when building envelopes; absent a deadline the wire key
# is omitted and envelopes stay byte-identical to the pre-deadline format.
_DEADLINE_MS: "contextvars.ContextVar[Optional[float]]" = contextvars.ContextVar(
    "inferd_deadline_ms", default=None
)


def current_deadline_ms() -> Optional[float]:
    """The active generation's absolute deadline (epoch ms), or None."""
    return _DEADLINE_MS.get()


def deadline_wire() -> Dict[str, float]:
    """{"deadline_ms": ...} for the active deadline, {} when none rides —
    splat into wire envelopes so deadline-less traffic stays byte-exact."""
    d = _DEADLINE_MS.get()
    return {retrylib.DEADLINE_KEY: d} if d is not None else {}


def _deadline_error(detail: str) -> ServerError:
    """The client-side flavor of the node's typed 408: non-retryable by
    construction (status < 500, code != session_state) — once the
    end-to-end budget is gone, another attempt can only waste work."""
    return ServerError(f"deadline exceeded: {detail}", 408, code="deadline")


def sample_np(
    logits: np.ndarray,  # [V] float32
    rng: np.random.Generator,
    temperature: float = 0.6,
    top_k: int = 20,
    top_p: float = 0.95,
    min_p: float = 0.0,
) -> int:
    """numpy mirror of inferd_tpu.core.sampling (same filter semantics —
    the reference's warper chain, client.py:95-120, plus min-p)."""
    logits = np.asarray(logits, dtype=np.float64)
    if temperature == 0.0:
        return int(np.argmax(logits))
    logits = logits / temperature
    if 0 < top_k < logits.shape[-1]:
        kth = np.partition(logits, -top_k)[-top_k]
        logits = np.where(logits < kth, -np.inf, logits)
    if top_p < 1.0:
        order = np.argsort(logits)[::-1]
        probs = _softmax(logits[order])
        cum = np.cumsum(probs)
        keep = (cum - probs) < top_p
        keep[0] = True
        drop = order[~keep]
        logits[drop] = -np.inf
    if min_p >= 1.0:
        raise ValueError(f"min_p must be in [0, 1), got {min_p}")
    if min_p > 0.0:
        logits = np.where(logits < np.max(logits) + np.log(min_p), -np.inf, logits)
    probs = _softmax(logits)
    return int(rng.choice(logits.shape[-1], p=probs))


def logprob_np(logits: np.ndarray, tok: int) -> float:
    """Model log-probability of `tok` under the UNWARPED logits (the
    standard serving-API meaning: what the model assigned, not what the
    sampler drew from). float64 log-softmax for stability."""
    l = np.asarray(logits, dtype=np.float64)
    l = l - np.max(l)
    return float(l[tok] - np.log(np.sum(np.exp(l))))


def top_logprobs_np(logits: np.ndarray, n: int):
    """Top-n (ids, logprobs) alternatives under the UNWARPED logits,
    descending — the serving-API top_logprobs surface. The client computes
    this locally from the logits it already receives every step."""
    l = np.asarray(logits, dtype=np.float64)
    l = l - np.max(l)
    lps = l - np.log(np.sum(np.exp(l)))
    idx = np.argsort(-lps, kind="stable")[:n]
    return idx.astype(int).tolist(), lps[idx].tolist()


async def _emit(cb, token) -> None:
    """Invoke a sync-or-async on_token callback."""
    r = cb(token)
    if asyncio.iscoroutine(r):
        await r


def _softmax(x: np.ndarray) -> np.ndarray:
    m = np.max(x[np.isfinite(x)]) if np.any(np.isfinite(x)) else 0.0
    e = np.exp(np.clip(x - m, -700, 0))
    s = e.sum()
    return e / s


class GenerationClient:
    """Base: the sampling/EOS/session loop over an abstract transport.

    Subclasses implement `_step` (one pipeline pass: token chunk in,
    last-token logits out) and `_end_session` (drop server-side KV).
    """

    def __init__(
        self,
        sampling: Optional[SamplingConfig] = None,
        tokenizer: Optional[Tokenizer] = None,
        timeout_s: float = 300.0,
        prefill_chunk: int = 512,
        adapter: Optional[str] = None,
    ):
        self.sampling = sampling or SamplingConfig()
        self.tokenizer = tokenizer
        self.timeout_s = timeout_s
        # multi-tenant LoRA: this client's sessions decode with the named
        # adapter (the per-session `adapter` envelope key, stamped on the
        # first chunk — admission maps it to a registry slot server-side;
        # None = the base model, envelopes byte-identical to pre-adapter)
        self.adapter = adapter
        # long prompts prefill in sequential chunks of this many tokens:
        # bounds the per-hop wire message and keeps every node compiling the
        # same bucketed shapes instead of one giant prompt-sized program
        # (the reference ships the full prompt in one request,
        # send_message.py:27-49 / client.py:217-236)
        self.prefill_chunk = max(1, prefill_chunk)
        self._http: Optional[ClientSession] = None
        # pinned prefixes: (prompt-prefix ids) -> (session_id, last logits).
        # The pinned session stays alive server-side (its per-stage KV is the
        # distributed prefix cache); generations whose prompt starts with a
        # pinned prefix FORK it instead of re-prefilling those tokens.
        # LRU-capped: each pin holds a [V] logits array here and a pinned
        # KV session per stage server-side — unbounded pins on a long-lived
        # client (e.g. the node's /generate self-client) would grow RSS and
        # crowd the servers' session stores.
        self._pins: "OrderedDict[tuple, tuple]" = OrderedDict()
        self.max_pins = 8
        self._pin_lock = asyncio.Lock()
        # per-client span ring (obs.trace): every generation records a
        # `generate` root span with per-step wire spans and per-token
        # sample spans under it; the trace context rides the /forward
        # envelope and the X-Inferd-Trace header so node-side spans merge
        # into the same end-to-end timeline. A co-located serving layer
        # (the node's /generate self-client) swaps in its own recorder so
        # all of a node's spans land in one JSONL file.
        self.tracer = tracelib.SpanRecorder(service="client")

    async def __aenter__(self):
        self._http = ClientSession(timeout=ClientTimeout(total=self.timeout_s))
        return self

    async def __aexit__(self, *exc) -> None:
        for ids in list(self._pins):
            sid, _ = self._pins.pop(ids)
            try:
                await self._end_session(sid)
            except Exception:
                pass  # best effort: nodes TTL-sweep orphaned sessions
        if self._http:
            await self._http.close()

    # -- transport interface (subclass responsibility) ----------------------

    async def _step(
        self, session_id: str, tokens: List[int], start_pos: int
    ) -> np.ndarray:
        """One pipeline pass; returns last-token logits [V]."""
        raise NotImplementedError

    async def _end_session(self, session_id: str) -> None:
        raise NotImplementedError

    async def _fork_session(
        self, new_session_id: str, parent_session_id: str, prefix_len: int
    ) -> bool:
        """Seed a new session from a parent's KV prefix on every stage.
        Default: unsupported (callers fall back to a full prefill)."""
        return False

    async def _traced_step(
        self, session_id: str, tokens: List[int], start_pos: int
    ) -> np.ndarray:
        """One pipeline pass wrapped in a `wire`-phase span: the envelope
        the subclass transport builds inside parents to this span (the
        contextvar carries it), so node-side spans nest under the step."""
        with self.tracer.span(
            "step", "wire", attrs={"start_pos": start_pos, "n": len(tokens)}
        ):
            return await self._step(session_id, tokens, start_pos)

    def _sample_traced(self, logits: np.ndarray, rng, s: SamplingConfig) -> int:
        """Client-side sampling with a `sample`-phase span (sub-ms, but it
        closes the per-token timeline: step + sample account for the whole
        decode iteration)."""
        t0 = tracelib.now()
        tok = sample_np(logits, rng, s.temperature, s.top_k, s.top_p, s.min_p)
        self.tracer.record_span(
            "sample", "sample", t0, tracelib.now(), parent=tracelib.current()
        )
        return tok

    # -- shared helpers ------------------------------------------------------

    async def _post_url(self, url: str, body: Dict[str, Any]) -> Dict[str, Any]:
        """POST a wire envelope; unpack defensively (a plain-HTTP error page
        or truncated body must surface the status, not a msgpack error).
        The active trace context (if any) rides as the X-Inferd-Trace
        header — the propagation surface for endpoints whose envelope has
        no `trace` key (/generate)."""
        assert self._http is not None, "use `async with <client>(...)`"
        headers = tracelib.header_ctx()
        kw: Dict[str, Any] = {}
        rem = retrylib.remaining_s(_DEADLINE_MS.get())
        if rem is not None:
            if rem <= 0:
                # the budget is gone: fail locally instead of shipping a
                # request every hop would only fast-fail anyway
                raise _deadline_error(f"before POST {url}")
            # per-request timeout = the smaller of the static client
            # timeout and what's left of the end-to-end budget (plus a
            # beat for the node's own typed 408 to make it back)
            kw["timeout"] = ClientTimeout(
                total=min(self.timeout_s, rem + 0.25)
            )
        async with self._http.post(
            url, data=wire.pack(body), headers=headers, **kw
        ) as r:
            raw = await r.read()
            try:
                data = wire.unpack(raw)
            except Exception:
                snippet = raw[:200].decode("utf-8", "replace")
                # ValueError: transport-level garbage (error page, truncated
                # stream) — callers with multiple endpoints treat it as
                # "this endpoint is bad" and fail over
                raise ValueError(f"{url} returned non-wire body (HTTP {r.status}): {snippet!r}")
            if r.status != 200:
                detail = data.get("error", data) if isinstance(data, dict) else data
                code = data.get("code") if isinstance(data, dict) else None
                ra = data.get("retry_after") if isinstance(data, dict) else None
                if ra is None:
                    # busy 503s also carry the standard header — parse it
                    # so a plain-HTTP shed (no wire body) still paces us
                    ra = r.headers.get("Retry-After")
                try:
                    ra = None if ra is None else float(ra)
                except (TypeError, ValueError):
                    ra = None
                rf = data.get("resume_from") if isinstance(data, dict) else None
                try:
                    rf = None if rf is None else int(rf)
                except (TypeError, ValueError):
                    rf = None
                raise ServerError(
                    f"{url} error {r.status}: {detail}", r.status, code,
                    retry_after=ra, resume_from=rf,
                )
            return data

    # -- public API ----------------------------------------------------------

    def pinned_parent(self, prefix_ids: Sequence[int]):
        """(parent_session_id, last-token logits) of a held pin, or None —
        lets a co-located serving layer (the node's speculative path) fork
        the pinned session directly instead of re-prefilling the prefix."""
        return self._pins.get(prefixlib.normalize_ids(prefix_ids))

    async def pin_prefix(self, prefix_ids: Sequence[int]) -> None:
        """Prefill `prefix_ids` under a dedicated long-lived session whose
        per-stage KV becomes a shared prefix cache: subsequent generations
        with a prompt starting in these ids fork it server-side instead of
        re-prefilling the prefix (the shared-system-prompt serving win).
        Pinned sessions are dropped on client exit."""
        ids = prefixlib.normalize_ids(prefix_ids)
        if ids in self._pins:
            self._pins.move_to_end(ids)
            return
        # single-flight: a burst of concurrent pins of the same prefix must
        # run ONE prefill, not N redundant ones with N-1 discarded sessions
        async with self._pin_lock:
            if ids in self._pins:
                self._pins.move_to_end(ids)
                return
            sid = str(uuid.uuid4())
            pos = 0
            logits: Optional[np.ndarray] = None
            for i in range(0, len(ids), self.prefill_chunk):
                chunk = list(ids[i : i + self.prefill_chunk])
                logits = await self._traced_step(sid, chunk, pos)
                pos += len(chunk)
            assert logits is not None
            self._pins[ids] = (sid, logits)
            while len(self._pins) > self.max_pins:
                _, (old_sid, _l) = self._pins.popitem(last=False)
                try:
                    await self._end_session(old_sid)
                except Exception:
                    pass  # best effort: servers TTL-sweep orphans

    def _longest_pin(self, prompt_ids: List[int]):
        return prefixlib.longest_prefix_match(self._pins, prompt_ids)

    async def generate_ids(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int = 64,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        session_retries: int = 2,
        retry_delay_s: float = 1.0,
        sampling: Optional[SamplingConfig] = None,
        on_token=None,
        logprob_sink: Optional[List[float]] = None,
        top_n: int = 0,
        top_sink: Optional[List] = None,
        deadline_s: Optional[float] = None,
        retry_cap_s: float = 8.0,
        retry_rng: Optional[random.Random] = None,
        retry_budget: Optional[retrylib.RetryBudget] = None,
    ) -> List[int]:
        """Prefill + token-by-token decode; returns the new ids.

        `logprob_sink` (optional list) collects each emitted token's model
        log-probability (log-softmax of the raw logits), in step with the
        returned ids; cleared at the start of every attempt so restarts
        stay consistent. `top_sink` with `top_n > 0` likewise collects the
        top-N (ids, logprobs) alternatives per step, computed client-side
        from the same logits.

        A mid-generation failure (a node died — its KV cache with it)
        restarts the WHOLE generation under a fresh session, up to
        `session_retries` times: the swarm needs a beat to detect the death
        (record TTL) and adopt the orphaned stage, after which the full
        prompt re-prefills on the adopting replica. Deterministic given the
        same seed, so a restart yields the same tokens.

        `on_token` (optional async or sync callable) is invoked with each
        new token id as it is sampled — the streaming hook. On a retried
        attempt it is called with None first (restart marker: previously
        streamed tokens are void, the deterministic re-run re-streams).

        Overload containment (docs/SERVING.md "Overload & reliability"):
        `deadline_s` stamps an absolute `deadline_ms` into every wire
        envelope — hops fast-fail with the typed non-retryable `deadline`
        error once the end-to-end budget is spent, and this loop stops
        retrying then too. Retry pacing is capped exponential backoff
        with FULL jitter (base `retry_delay_s`, cap `retry_cap_s`;
        `retry_rng` seeds it for deterministic tests), raised to a busy
        node's `Retry-After` hint when one rides the 503. Every retry
        spends a token from `retry_budget` (default: the per-process
        bucket shared across sessions) — when the bucket is dry the
        ORIGINAL error surfaces instead of amplifying a storm."""
        if not prompt_ids:
            raise ValueError("prompt_ids must be non-empty")
        budget = retry_budget or retrylib.DEFAULT_RETRY_BUDGET
        rng = retry_rng  # None -> module-level random (decorrelated)
        dl_token = None
        if deadline_s is not None:
            dl_token = _DEADLINE_MS.set(
                retrylib.deadline_ms_from_now(deadline_s)
            )
        # root span of the end-to-end timeline: one trace per generation,
        # retries included (restart attempts show up as extra step spans)
        try:
            with self.tracer.span(
                "generate", "client",
                attrs={"prompt": len(prompt_ids), "max_new": max_new_tokens},
            ):
                last_err: Optional[Exception] = None
                for attempt in range(1 + session_retries):
                    if attempt:
                        assert last_err is not None
                        if not budget.try_acquire():
                            # retry budget dry: bounded retry rate beats a
                            # storm — surface the ORIGINAL failure
                            raise last_err
                        delay = retrylib.backoff_delay(
                            attempt, retry_delay_s, retry_cap_s, rng
                        )
                        ra = getattr(last_err, "retry_after", None)
                        if ra is not None:
                            # a shedding node said when to come back:
                            # honor it (jitter still rides on top)
                            delay = max(delay, float(ra))
                        rem = retrylib.remaining_s(_DEADLINE_MS.get())
                        if rem is not None and rem <= delay:
                            # the budget can't survive the wait: stop now
                            raise _deadline_error(
                                "retry pacing exceeds the remaining budget"
                            ) from last_err
                        await asyncio.sleep(delay)
                        if on_token is not None:
                            await _emit(on_token, None)
                    try:
                        return await self._generate_once(
                            list(prompt_ids), max_new_tokens, eos_token_id, seed,
                            sampling or self.sampling, on_token, logprob_sink,
                            top_n, top_sink,
                        )
                    except ServerError as e:
                        if not e.retryable:
                            raise  # deterministic failure: retrying cannot succeed
                        last_err = e
                    except (
                        ConnectionError, OSError, asyncio.TimeoutError, aiohttp.ClientError
                    ) as e:
                        # transport-level death (includes ServerDisconnectedError /
                        # ClientPayloadError, which are ClientError but NOT OSError —
                        # the chain client posts raw, without SwarmClient's
                        # ConnectionError wrapping)
                        last_err = e
                assert last_err is not None
                raise last_err
        finally:
            if dl_token is not None:
                _DEADLINE_MS.reset(dl_token)

    async def _step_resuming(
        self, session_id: str, toks: List[int], pos: int,
        known: List[int], resumes: List[int],
    ) -> np.ndarray:
        """_traced_step with standby-promotion resume: a session_state
        409 carrying `resume_from` F means the answering replica holds
        the session's REPLICATED KV up to F (async standby replication,
        runtime/repl) — re-send only known[F:pos], the tokens past the
        replication frontier, and retry the step. The session id and
        every already-emitted token survive: this is a bounded tail
        re-prefill, not a restart. `known` is the absolute token stream
        (prompt + generated so far), `resumes` a one-element mutable
        budget shared across the generation so a flapping fleet can't
        loop us; exhausted/ineligible errors propagate into the ordinary
        full-restart retry loop — exactly the pre-replication behavior."""
        try:
            return await self._traced_step(session_id, toks, pos)
        except ServerError as e:
            f = e.resume_from
            if f is None or not 0 <= int(f) < pos or resumes[0] <= 0:
                raise
            resumes[0] -= 1
            p = int(f)
            replay = known[p:pos]
            for i in range(0, len(replay), self.prefill_chunk):
                chunk = replay[i : i + self.prefill_chunk]
                # replay chunks resume too (budget-bounded recursion): a
                # multi-stage pipeline may hold a LOWER frontier on
                # another stage's standby, and its offer surfaces on the
                # REPLAY chunk that first reaches that stage — each offer
                # walks the resume point back until every stage can serve
                await self._step_resuming(
                    session_id, chunk, p, known, resumes
                )
                p += len(chunk)
            return await self._step_resuming(
                session_id, toks, pos, known, resumes
            )

    async def _generate_once(
        self,
        prompt_ids: List[int],
        max_new_tokens: int,
        eos_token_id: Optional[int],
        seed: int,
        sampling: Optional[SamplingConfig] = None,
        on_token=None,
        logprob_sink: Optional[List[float]] = None,
        top_n: int = 0,
        top_sink: Optional[List] = None,
    ) -> List[int]:
        session_id = str(uuid.uuid4())
        rng = np.random.default_rng(seed)
        s = sampling or self.sampling
        out: List[int] = []
        # absolute token stream + resume budget for _step_resuming (the
        # standby-promotion partial-restart path)
        known: List[int] = list(prompt_ids)
        resumes = [4]
        if logprob_sink is not None:
            logprob_sink.clear()  # deterministic restarts re-fill
        if top_sink is not None:
            top_sink.clear()
        try:
            pos = 0
            logits: Optional[np.ndarray] = None
            pin = self._longest_pin(prompt_ids)
            if pin is not None:
                parent_sid, pin_logits = self._pins[pin]
                self._pins.move_to_end(pin)  # LRU: reuse refreshes the pin
                forked = transient = False
                try:
                    forked = await self._fork_session(
                        session_id, parent_sid, len(pin)
                    )
                except Exception:
                    # transport-level trouble: the parent may be perfectly
                    # alive — keep the pin for the next generation
                    transient = True
                if forked:
                    pos = len(pin)
                    logits = pin_logits  # used as-is when the prompt IS the pin
                else:
                    if not transient:
                        # clean miss (ok=False): the parent is truly gone
                        # (evicted / node died / executor without forking) —
                        # a stale pin would miss on every future call too
                        self._pins.pop(pin, None)
                    # clean any partially-forked stages, then fall back to
                    # the full prefill below
                    try:
                        await self._end_session(session_id)
                    except Exception:
                        pass
            for i in range(pos, len(prompt_ids), self.prefill_chunk):
                chunk = prompt_ids[i : i + self.prefill_chunk]
                logits = await self._step_resuming(
                    session_id, chunk, pos, known, resumes
                )
                pos += len(chunk)
            assert logits is not None
            tok = self._sample_traced(logits, rng, s)
            out.append(tok)
            known.append(tok)
            if logprob_sink is not None:
                logprob_sink.append(logprob_np(logits, tok))
            if top_sink is not None:
                top_sink.append(top_logprobs_np(logits, top_n))
            if on_token is not None:
                await _emit(on_token, tok)
            while len(out) < max_new_tokens and tok != eos_token_id:
                logits = await self._step_resuming(
                    session_id, [tok], pos, known, resumes
                )
                pos += 1
                tok = self._sample_traced(logits, rng, s)
                out.append(tok)
                known.append(tok)
                if logprob_sink is not None:
                    logprob_sink.append(logprob_np(logits, tok))
                if top_sink is not None:
                    top_sink.append(top_logprobs_np(logits, top_n))
                if on_token is not None:
                    await _emit(on_token, tok)
        finally:
            try:
                await self._end_session(session_id)
            except Exception:
                pass  # best effort: nodes TTL-sweep orphaned sessions
        return out

    async def generate(
        self, prompt: str, max_new_tokens: int = 64, seed: int = 0, chat: bool = True
    ) -> str:
        """Text in, text out (chat template when the tokenizer has one)."""
        tok = self.tokenizer or Tokenizer()
        if chat:
            ids = tok.apply_chat_template(
                [{"role": "user", "content": prompt}], add_generation_prompt=True
            )
        else:
            ids = tok.encode(prompt)
        new_ids = await self.generate_ids(
            ids, max_new_tokens, eos_token_id=tok.eos_token_id, seed=seed
        )
        return tok.decode(new_ids)
