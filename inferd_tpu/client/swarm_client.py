"""Swarm generation client: drives the pipeline with client-side sampling.

Capability parity with both reference clients — the swarm token loop
(/root/reference/petals/send_message.py:27-60) and the gRPC generation
client (/root/reference/models/qwen3/client/client.py:204-287) — unified:
the client sends tokens to any stage-0 node and receives last-token logits
from the last stage (relay unwind), samples locally (temperature/top-k/
top-p, the reference's warper chain), and keeps per-session KV on the
nodes. Pure numpy — importing this never initializes JAX (a TPU client
machine shouldn't claim a chip to sample 20 logits).

The outer generation loop lives in client.base.GenerationClient (shared
with ChainClient); this class supplies the relay transport: every chunk
enters at a stage-0 node and the swarm routes it onward.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import aiohttp
import numpy as np

from inferd_tpu.client.base import GenerationClient, sample_np  # noqa: F401 (re-export)
from inferd_tpu.config import SamplingConfig
from inferd_tpu.core.tokenizer import Tokenizer
from inferd_tpu.utils import retry as retrylib

log = logging.getLogger(__name__)


class SwarmClient(GenerationClient):
    """Async client for a running swarm (relay topology)."""

    def __init__(
        self,
        entry_nodes: Sequence[Tuple[str, int]],
        sampling: Optional[SamplingConfig] = None,
        tokenizer: Optional[Tokenizer] = None,
        timeout_s: float = 300.0,
        prefill_chunk: int = 512,
        adapter: Optional[str] = None,
    ):
        if not entry_nodes:
            raise ValueError("need at least one entry node address")
        super().__init__(
            sampling, tokenizer, timeout_s, prefill_chunk, adapter=adapter
        )
        self.entry_nodes = [tuple(a) for a in entry_nodes]

    async def _post(self, path: str, body: Dict[str, Any]) -> Dict[str, Any]:
        """POST to the first healthy entry node (stage-0 failover)."""
        from inferd_tpu.client.base import ServerError

        last_err: Optional[Exception] = None
        for host, port in self.entry_nodes:
            try:
                return await self._post_url(f"http://{host}:{port}{path}", body)
            except (OSError, asyncio.TimeoutError, aiohttp.ClientError, ValueError) as e:
                # ValueError: non-wire/truncated body (base._post_url) — the
                # endpoint is broken even if it spoke HTTP; try the next one
                last_err = e
                log.warning("entry node %s:%d unreachable: %s", host, port, e)
            except ServerError as e:
                if e.status < 500:
                    raise  # deterministic (400/409...): another entry won't differ
                # 5xx: THIS entry is unhealthy (e.g. draining mid-shutdown).
                # Another entry can serve the chunk — mid-session ones too,
                # now that nodes advertise session locations via gossip and
                # relay to the KV holder (runtime/node.py rescue path).
                last_err = e
                log.warning("entry node %s:%d unhealthy: %s", host, port, e)
        if isinstance(last_err, ServerError):
            raise last_err
        raise ConnectionError(f"no entry node reachable: {last_err}")

    def _forward_env(self, session_id: str, tokens: List[int], start_pos: int):
        """The ONE /forward envelope definition (entry-routed _step and the
        direct-URL disaggregated decode share it). The active trace
        context rides as a `trace` key next to session_id/task_id; with
        tracing disabled (INFERD_TRACE=0) the key is OMITTED so the
        envelope stays byte-identical to the untraced format. The active
        end-to-end deadline rides the same way (`deadline_ms`, omitted
        when no deadline is set — old peers ignore the key, deadline-less
        traffic stays byte-exact). A client bound to a tenant adapter
        stamps the `adapter` key on the FIRST chunk only (start_pos 0 —
        admission binds the session; omitted otherwise, so base-model
        envelopes stay byte-identical)."""
        from inferd_tpu.client.base import deadline_wire
        from inferd_tpu.obs import trace as tracelib

        return tracelib.attach_wire({
            "task_id": str(uuid.uuid4()),
            "session_id": session_id,
            "stage": 0,
            "payload": {
                "tokens": np.asarray([tokens], dtype=np.int32),
                "start_pos": start_pos,
                "real_len": len(tokens),
                **(
                    {"adapter": self.adapter}
                    if self.adapter is not None and start_pos == 0 else {}
                ),
            },
            **deadline_wire(),
        })

    async def _step(
        self, session_id: str, tokens: List[int], start_pos: int
    ) -> np.ndarray:
        resp = await self._post(
            "/forward", self._forward_env(session_id, tokens, start_pos)
        )
        result = resp["result_for_user"]
        return np.asarray(result["logits"])[0]

    async def _end_session(self, session_id: str) -> None:
        await self._post("/end_session", {"session_id": session_id, "stage": 0})

    async def generate_ids_disaggregated(
        self,
        prompt_ids: Sequence[int],
        decode_node: Tuple[str, int],
        max_new_tokens: int = 64,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        sampling: Optional[SamplingConfig] = None,
    ) -> List[int]:
        """DISAGGREGATED prefill->decode: prefill on this client's entry
        replica (wherever capacity for the long compute-bound prefill
        is), hand the session's KV to `decode_node` via /export_session,
        and run the bandwidth-bound decode loop THERE — token-exact with
        a single-replica generation, zero restarts. The reference pins a
        session's KV to one server forever (qwen3_server_module.py:220);
        this build's handoff codec makes placement a per-phase choice."""
        from inferd_tpu.client.base import ServerError, sample_np

        if not prompt_ids:
            raise ValueError("prompt_ids must be non-empty")
        s = sampling or self.sampling
        rng = np.random.default_rng(seed)
        sid = str(uuid.uuid4())
        dh, dp = decode_node
        durl = f"http://{dh}:{dp}"
        out: List[int] = []
        handed_off = False
        try:
            # phase 1: chunked prefill on the entry replica
            pos = 0
            logits = None
            ids = [int(t) for t in prompt_ids]
            for i in range(0, len(ids), self.prefill_chunk):
                chunk = ids[i : i + self.prefill_chunk]
                logits = await self._step(sid, chunk, pos)
                pos += len(chunk)
            assert logits is not None
            # phase 2: hand the session to the decode replica
            resp = await self._post(
                "/export_session",
                {"session_id": sid, "target_host": dh, "target_port": dp},
            )
            if not resp.get("ok"):
                raise ServerError(f"handoff declined: {resp}", 502)
            # phase 3: decode against the target, token-exact
            tok = sample_np(logits, rng, s.temperature, s.top_k, s.top_p, s.min_p)
            out.append(tok)
            handed_off = True
            while len(out) < max_new_tokens and tok != eos_token_id:
                r = await self._post_url(
                    f"{durl}/forward", self._forward_env(sid, [tok], pos)
                )
                logits = np.asarray(r["result_for_user"]["logits"])[0]
                pos += 1
                tok = sample_np(logits, rng, s.temperature, s.top_k, s.top_p, s.min_p)
                out.append(tok)
        finally:
            try:
                await self._post_url(
                    f"{durl}/end_session", {"session_id": sid, "stage": 0}
                )
            except Exception:
                pass  # best effort: TTL sweep collects orphans
            if not handed_off:
                # a failure BEFORE the handoff leaves the session (a
                # pinned lane on batched replicas) on the ENTRY node —
                # free it now, not at the TTL sweep
                try:
                    await self._end_session(sid)
                except Exception:
                    pass
        return out

    async def generate_server_side(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int = 64,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        pin_prefix_len: int = 0,
        sampling: Optional[SamplingConfig] = None,
        logprob_sink: Optional[List[float]] = None,
        top_logprobs: int = 0,
        top_sink: Optional[List] = None,
        return_payload: bool = False,
        deadline_s: Optional[float] = None,
    ) -> List[int]:
        """One-round-trip generation: the NODE runs the token loop against
        itself (/generate) and returns the finished ids — for clients far
        from the swarm, where a per-token round trip would dominate.
        `pin_prefix_len` marks the first N prompt ids as a shared prefix the
        node pins and forks server-side. `logprob_sink` (the same out-param
        convention as generate_ids — stable return type) collects each
        token's model log-probability; `top_sink` with `top_logprobs > 0`
        collects per-token (top_ids, top_lps) alternatives.
        `return_payload=True` returns the node's whole reply dict instead
        of just ids (e.g. `speculative`/`spec_accept_rate` telemetry)."""
        s = sampling or self.sampling
        want_lp = logprob_sink is not None
        # client root span: makes _post_url send the X-Inferd-Trace header,
        # so the node's server-side token loop joins THIS trace and the
        # merged timeline keeps the client's wall-clock view
        with self.tracer.span(
            "generate", "client",
            attrs={"prompt": len(prompt_ids), "max_new": max_new_tokens,
                   "server_side": True},
        ):
            resp = await self._post(
                "/generate",
                {
                    "prompt_ids": [int(t) for t in prompt_ids],
                    "max_new_tokens": max_new_tokens,
                    "eos_token_id": eos_token_id,
                    "seed": seed,
                    "pin_prefix_len": pin_prefix_len,
                    # end-to-end budget for the WHOLE server-driven
                    # generation; rides only when set (old nodes ignore
                    # the key, deadline-less bodies stay byte-identical)
                    **(
                        {"deadline_ms":
                         retrylib.deadline_ms_from_now(deadline_s)}
                        if deadline_s is not None else {}
                    ),
                    # like min_p below: only ride when set (rolling upgrades)
                    **({"logprobs": True} if want_lp else {}),
                    **({"top_logprobs": top_logprobs} if top_logprobs else {}),
                    # min_p rides only when set: pre-min-p nodes reject
                    # unknown sampling keys (rolling-upgrade compatibility)
                    "sampling": {
                        "temperature": s.temperature,
                        "top_k": s.top_k,
                        "top_p": s.top_p,
                        **({"min_p": s.min_p} if s.min_p else {}),
                    },
                },
            )
        ids = [int(t) for t in resp["ids"]]
        if want_lp:
            logprob_sink.clear()
            logprob_sink.extend(float(x) for x in resp.get("logprobs") or [])
        if top_sink is not None:
            top_sink.clear()
            top_sink.extend(
                ([int(i) for i in ti], [float(x) for x in tl])
                for ti, tl in (resp.get("top_logprobs") or [])
            )
        if return_payload:
            return resp
        return ids

    async def generate_server_side_stream(
        self,
        prompt_ids: Sequence[int],
        on_token,
        max_new_tokens: int = 64,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        pin_prefix_len: int = 0,
        sampling: Optional[SamplingConfig] = None,
    ) -> List[int]:
        """Streaming flavor of generate_server_side: `on_token(id)` fires as
        each token arrives (None = restart marker — previously streamed
        tokens are void); returns the final ids. Transport is chunked
        newline-delimited JSON from the node's /generate."""
        from inferd_tpu.runtime import wire

        s = sampling or self.sampling
        body = wire.pack(
            {
                "prompt_ids": [int(t) for t in prompt_ids],
                "max_new_tokens": max_new_tokens,
                "eos_token_id": eos_token_id,
                "seed": seed,
                "pin_prefix_len": pin_prefix_len,
                "stream": True,
                # min_p rides only when set: pre-min-p nodes reject
                # unknown sampling keys (rolling-upgrade compatibility)
                "sampling": {
                    "temperature": s.temperature,
                    "top_k": s.top_k,
                    "top_p": s.top_p,
                    **({"min_p": s.min_p} if s.min_p else {}),
                },
            }
        )
        assert self._http is not None, "use `async with SwarmClient(...)`"
        # per-request timeout: the session-wide ClientTimeout(total=...)
        # would cap the WHOLE stream, making generations longer than
        # timeout_s impossible; bound inactivity between chunks instead
        # (tokens arrive continuously while the generation is healthy)
        stream_timeout = aiohttp.ClientTimeout(
            total=None, sock_connect=min(self.timeout_s, 60.0),
            sock_read=self.timeout_s,
        )
        from inferd_tpu.obs import trace as tracelib

        # client root span (see generate_server_side): without it no
        # X-Inferd-Trace header ever rides, and a standalone client's
        # server-driven streams would be invisible in merged timelines
        with self.tracer.span(
            "generate", "client",
            attrs={"prompt": len(prompt_ids), "max_new": max_new_tokens,
                   "server_side": True, "stream": True},
        ):
            trace_headers = tracelib.header_ctx()
            return await self._stream_entry_loop(
                body, stream_timeout, trace_headers, on_token
            )

    async def _stream_entry_loop(
        self, body, stream_timeout, trace_headers, on_token
    ) -> List[int]:
        """The entry-node failover loop of generate_server_side_stream
        (split out so the root span wraps it cleanly)."""
        import json as jsonlib

        from inferd_tpu.client.base import _emit

        last_err: Optional[Exception] = None
        emitted_any = False
        for host, port in self.entry_nodes:
            url = f"http://{host}:{port}/generate"
            try:
                async with self._http.post(
                    url, data=body, timeout=stream_timeout,
                    headers=trace_headers,
                ) as r:
                    if r.status != 200:
                        # deterministic app error (400/409...): preserve the
                        # ServerError status/code contract — do NOT fail over
                        # and retry the identical bad request
                        from inferd_tpu.client.base import ServerError
                        from inferd_tpu.runtime import wire as wirelib

                        raw = await r.read()
                        try:
                            data = wirelib.unpack(raw)
                        except Exception:
                            data = {}
                        detail = data.get("error", raw[:200]) if isinstance(data, dict) else raw[:200]
                        code = data.get("code") if isinstance(data, dict) else None
                        raise ServerError(
                            f"{url} error {r.status}: {detail}", r.status, code
                        )
                    ids: Optional[List[int]] = None
                    # manual line splitting over iter_any(): aiohttp's line
                    # iterator caps a line at ~64 KB, which the terminal
                    # {"done", "ids": [...]} line exceeds on long generations
                    buf = b""
                    async for chunk in r.content.iter_any():
                        buf += chunk
                        while b"\n" in buf:
                            line, buf = buf.split(b"\n", 1)
                            if not line.strip():
                                continue
                            obj = jsonlib.loads(line)
                            if "t" in obj:
                                emitted_any = True
                                await _emit(on_token, int(obj["t"]))
                            elif obj.get("restart"):
                                await _emit(on_token, None)
                            elif obj.get("done"):
                                ids = [int(t) for t in obj["ids"]]
                            elif "error" in obj:
                                raise RuntimeError(
                                    f"server-side generation: {obj['error']}"
                                )
                    if ids is None:
                        raise ConnectionError(f"{url} stream ended without done line")
                    return ids
            except (OSError, asyncio.TimeoutError, aiohttp.ClientError) as e:
                last_err = e
                log.warning("entry node %s:%d unreachable: %s", host, port, e)
                if emitted_any:
                    # failing over re-streams from scratch on the next node:
                    # void what the consumer already saw (same contract as
                    # the server-side retry's restart marker)
                    await _emit(on_token, None)
                    emitted_any = False
        raise ConnectionError(f"no entry node reachable: {last_err}")

    async def _fork_session(
        self, new_session_id: str, parent_session_id: str, prefix_len: int
    ) -> bool:
        """Fork the parent's per-stage KV prefix swarm-wide: the request
        enters at stage 0 and relays along the parent's affinity route."""
        resp = await self._post(
            "/fork_session",
            {
                "session_id": new_session_id,
                "parent_session_id": parent_session_id,
                "prefix_len": prefix_len,
                "stage": 0,
            },
        )
        return bool(resp.get("ok"))
