"""Swarm generation client: drives the pipeline with client-side sampling.

Capability parity with both reference clients — the swarm token loop
(/root/reference/petals/send_message.py:27-60) and the gRPC generation
client (/root/reference/models/qwen3/client/client.py:204-287) — unified:
the client sends tokens to any stage-0 node and receives last-token logits
from the last stage (relay unwind), samples locally (temperature/top-k/
top-p, the reference's warper chain), and keeps per-session KV on the
nodes. Pure numpy — importing this never initializes JAX (a TPU client
machine shouldn't claim a chip to sample 20 logits).
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import aiohttp
import numpy as np
from aiohttp import ClientSession, ClientTimeout

from inferd_tpu.config import SamplingConfig
from inferd_tpu.core.tokenizer import Tokenizer
from inferd_tpu.runtime import wire

log = logging.getLogger(__name__)


def sample_np(
    logits: np.ndarray,  # [V] float32
    rng: np.random.Generator,
    temperature: float = 0.6,
    top_k: int = 20,
    top_p: float = 0.95,
) -> int:
    """numpy mirror of inferd_tpu.core.sampling (same filter semantics)."""
    logits = np.asarray(logits, dtype=np.float64)
    if temperature == 0.0:
        return int(np.argmax(logits))
    logits = logits / temperature
    if 0 < top_k < logits.shape[-1]:
        kth = np.partition(logits, -top_k)[-top_k]
        logits = np.where(logits < kth, -np.inf, logits)
    if top_p < 1.0:
        order = np.argsort(logits)[::-1]
        probs = _softmax(logits[order])
        cum = np.cumsum(probs)
        keep = (cum - probs) < top_p
        keep[0] = True
        drop = order[~keep]
        logits[drop] = -np.inf
    probs = _softmax(logits)
    return int(rng.choice(logits.shape[-1], p=probs))


def _softmax(x: np.ndarray) -> np.ndarray:
    m = np.max(x[np.isfinite(x)]) if np.any(np.isfinite(x)) else 0.0
    e = np.exp(np.clip(x - m, -700, 0))
    s = e.sum()
    return e / s


class SwarmClient:
    """Async client for a running swarm."""

    def __init__(
        self,
        entry_nodes: Sequence[Tuple[str, int]],
        sampling: Optional[SamplingConfig] = None,
        tokenizer: Optional[Tokenizer] = None,
        timeout_s: float = 300.0,
    ):
        if not entry_nodes:
            raise ValueError("need at least one entry node address")
        self.entry_nodes = [tuple(a) for a in entry_nodes]
        self.sampling = sampling or SamplingConfig()
        self.tokenizer = tokenizer
        self.timeout_s = timeout_s
        self._http: Optional[ClientSession] = None

    async def __aenter__(self) -> "SwarmClient":
        self._http = ClientSession(timeout=ClientTimeout(total=self.timeout_s))
        return self

    async def __aexit__(self, *exc) -> None:
        if self._http:
            await self._http.close()

    async def _post(self, path: str, body: Dict[str, Any]) -> Dict[str, Any]:
        assert self._http is not None, "use `async with SwarmClient(...)`"
        last_err: Optional[Exception] = None
        for host, port in self.entry_nodes:
            try:
                async with self._http.post(
                    f"http://{host}:{port}{path}", data=wire.pack(body)
                ) as r:
                    data = wire.unpack(await r.read())
                    if r.status != 200:
                        raise RuntimeError(
                            f"swarm error {r.status}: {data.get('error', data)}"
                        )
                    return data
            except (OSError, asyncio.TimeoutError, aiohttp.ClientError, ValueError) as e:
                # ClientError: disconnects/transport faults that aren't
                # OSError subclasses; ValueError: truncated/non-msgpack body
                last_err = e
                log.warning("entry node %s:%d unreachable: %s", host, port, e)
        raise ConnectionError(f"no entry node reachable: {last_err}")

    async def _step(
        self, session_id: str, tokens: List[int], start_pos: int
    ) -> np.ndarray:
        resp = await self._post(
            "/forward",
            {
                "task_id": str(uuid.uuid4()),
                "session_id": session_id,
                "stage": 0,
                "payload": {
                    "tokens": np.asarray([tokens], dtype=np.int32),
                    "start_pos": start_pos,
                    "real_len": len(tokens),
                },
            },
        )
        result = resp["result_for_user"]
        return np.asarray(result["logits"])[0]

    async def generate_ids(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int = 64,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
    ) -> List[int]:
        """Token-by-token pipeline generation; returns new ids."""
        if not prompt_ids:
            raise ValueError("prompt_ids must be non-empty")
        session_id = str(uuid.uuid4())
        rng = np.random.default_rng(seed)
        s = self.sampling
        out: List[int] = []
        try:
            logits = await self._step(session_id, list(prompt_ids), 0)
            pos = len(prompt_ids)
            tok = sample_np(logits, rng, s.temperature, s.top_k, s.top_p)
            out.append(tok)
            while len(out) < max_new_tokens and tok != eos_token_id:
                logits = await self._step(session_id, [tok], pos)
                pos += 1
                tok = sample_np(logits, rng, s.temperature, s.top_k, s.top_p)
                out.append(tok)
        finally:
            try:
                await self._post(
                    "/end_session", {"session_id": session_id, "stage": 0}
                )
            except Exception:
                pass  # nodes TTL-sweep orphaned sessions
        return out

    async def generate(
        self, prompt: str, max_new_tokens: int = 64, seed: int = 0, chat: bool = True
    ) -> str:
        """Text in, text out (chat template when the tokenizer has one)."""
        tok = self.tokenizer or Tokenizer()
        if chat:
            ids = tok.apply_chat_template(
                [{"role": "user", "content": prompt}], add_generation_prompt=True
            )
        else:
            ids = tok.encode(prompt)
        new_ids = await self.generate_ids(
            ids, max_new_tokens, eos_token_id=tok.eos_token_id, seed=seed
        )
        return tok.decode(new_ids)
