"""Client / API layer (L4): swarm (relay) and chain (hub-and-spoke)
generation clients over a shared sampling/session front end."""

from inferd_tpu.client.base import GenerationClient, sample_np
from inferd_tpu.client.chain_client import ChainClient
from inferd_tpu.client.swarm_client import SwarmClient

__all__ = ["GenerationClient", "sample_np", "SwarmClient", "ChainClient"]
