"""Client / API layer (L4): swarm generation client."""
