"""DHT-routed chain client: D*-Lite plans the chain, live costs replan it.

This closes the reference's signature gap end to end: its D*-Lite module
(/root/reference/dstar/dstarlite.py) was built to pick the gRPC chain but
never wired — `Qwen3Client._find_best_chain` was a dead stub
(/root/reference/models/qwen3/client/client.py:131-138) and the chain stayed
the hardcoded `server_addrs` order (rpc_client.py:16-20). Here the chain is
PLANNED per session over the live gossip view and REPLANNED incrementally
while the session's first pass is still walking it:

  * the client joins the gossip store as a records-less observer (it
    announces nothing; it merges everyone's {load, cap, svc_ms} records);
  * a new session builds a `SwarmChainPlanner` (one D*-Lite instance) and
    walks stage by stage hub-and-spoke (`/forward` with relay=False, the
    ChainClient topology); after each hop it calls `advance` (D*-Lite
    `advance_start` — the agent moved, its KV is committed there) and
    refreshes edge costs from the gossip view — a load spike on a replica
    planned for a LATER stage replans the remaining hops incrementally
    (update_edge + a bounded compute), so the pass lands on the better
    replica before any KV commits there;
  * once the first pass completes, the chain is FROZEN for the session:
    every stage now holds its KV, and later chunks/decode steps must go
    where the KV lives (the planner's job is initial placement; moving a
    live session is the balancer's live-handoff machinery, node.py
    change_stage).

`planner_stats(session_id)` exposes the D*-Lite counters (expansions per
build vs per replan) so the incremental property is testable end to end.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from inferd_tpu.client.base import GenerationClient, ServerError
from inferd_tpu.config import SamplingConfig
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.control.dstar import SwarmChainPlanner
from inferd_tpu.core.tokenizer import Tokenizer

log = logging.getLogger(__name__)


class _SessionPlan:
    """Per-session routing state: the planner while walking, the frozen
    chain once committed."""

    __slots__ = ("planner", "chain", "committed", "stats")

    def __init__(self, planner: Optional[SwarmChainPlanner]):
        self.planner = planner
        self.chain: List[Tuple[str, Dict[str, Any]]] = []  # [(node_id, value)]
        self.committed = False
        self.stats: Optional[Dict[str, int]] = None  # planner stats at freeze


class RoutedChainClient(GenerationClient):
    """Hub-and-spoke chain client whose chain comes from D*-Lite over the
    live swarm view instead of a fixed `server_addrs` list.

    `dht` must be a started SwarmDHT that bootstraps into the swarm (the
    client never announces — it is a pure observer; see
    control/dht.py's records-less-peer handling). `hop_hook`, when set, is
    awaited between first-pass hops — instrumentation/testing surface (e.g.
    inject a load spike and assert the replan)."""

    def __init__(
        self,
        dht: SwarmDHT,
        num_stages: int,
        sampling: Optional[SamplingConfig] = None,
        tokenizer: Optional[Tokenizer] = None,
        timeout_s: float = 300.0,
        prefill_chunk: int = 512,
    ):
        super().__init__(sampling, tokenizer, timeout_s, prefill_chunk)
        self.dht = dht
        self.num_stages = num_stages
        self._plans: Dict[str, _SessionPlan] = {}
        self.hop_hook = None  # async (session_id, completed_stage) -> None

    # ------------------------------------------------------------- planning

    def _snapshot(self) -> Dict[int, Dict[str, Dict[str, Any]]]:
        return self.dht.get_all(self.num_stages)

    def planner_stats(self, session_id: str) -> Optional[Dict[str, int]]:
        """Live planner counters while walking; the frozen snapshot after."""
        plan = self._plans.get(session_id)
        if plan is None:
            return None
        if plan.planner is not None:
            return dict(plan.planner.stats)
        return dict(plan.stats) if plan.stats else None

    def _plan_for(self, session_id: str) -> _SessionPlan:
        plan = self._plans.get(session_id)
        if plan is None:
            plan = _SessionPlan(
                SwarmChainPlanner(self._snapshot(), 0, self.num_stages)
            )
            self._plans[session_id] = plan
        return plan

    @staticmethod
    def _routing_unavailable(e: Exception) -> ServerError:
        """Planning failures surface as a RETRYABLE 503: a stage with no
        live replica in the observer's view is the same transient condition
        the swarm relay reports as 503 (a lost gossip round, a node mid-
        adoption) — generate_ids' session-retry loop must get its chance.
        Persistent emptiness exhausts the retries and surfaces this error."""
        return ServerError(f"routing unavailable: {e}", 503, code="no_chain")

    @staticmethod
    def _addr(value: Dict[str, Any]) -> Tuple[str, int]:
        return (value["host"], int(value["port"]))

    # ------------------------------------------------------------ transport

    async def _post(self, addr: Tuple[str, int], path: str, body: Dict[str, Any]):
        host, port = addr
        return await self._post_url(f"http://{host}:{port}{path}", body)

    async def _hop(
        self,
        addr: Tuple[str, int],
        stage: int,
        session_id: str,
        payload: Dict[str, Any],
    ) -> Dict[str, Any]:
        from inferd_tpu.client.base import deadline_wire
        from inferd_tpu.obs import trace as tracelib

        # per-hop wire span (send/recv anchors for skew correction); the
        # envelope `trace` key is omitted when tracing is disabled, and
        # `deadline_ms` (the active end-to-end budget) rides the same way
        with self.tracer.span("hop", "wire", attrs={"stage": stage}):
            env = tracelib.attach_wire({
                "task_id": str(uuid.uuid4()),
                "session_id": session_id,
                "stage": stage,
                "relay": False,
                "payload": payload,
                **deadline_wire(),
            })
            resp = await self._post(addr, "/forward", env)
        return resp["result"]

    async def _step(
        self, session_id: str, tokens: List[int], start_pos: int
    ) -> np.ndarray:
        plan = self._plan_for(session_id)
        payload: Dict[str, Any] = {
            "tokens": np.asarray([tokens], dtype=np.int32),
            "start_pos": start_pos,
            "real_len": len(tokens),
        }
        if plan.committed:
            # KV lives on these replicas now: the chain is fixed for the
            # session's remaining chunks/decode steps. A hop that DIES
            # mid-session is rescued via the gossip session-location
            # adverts the client already merges (the `sess` hashes in
            # node records): if another same-stage replica advertises this
            # session's KV (graceful-shutdown handoff, balancer
            # migration), the chain is REPAIRED to point there and the
            # generation continues without a session restart — the same
            # capability the swarm relay path got in round 3
            # (runtime.node._gossip_session_holder); only when no holder
            # is advertised does the failure surface to generate_ids'
            # session-restart retry loop.
            for stage, (nid, value) in enumerate(plan.chain):
                try:
                    result = await self._hop(
                        self._addr(value), stage, session_id, payload
                    )
                except Exception as e:
                    if not self._hop_failure_rescuable(e):
                        raise
                    nid, value = self._find_session_holder(
                        session_id, stage, exclude=nid, cause=e
                    )
                    plan.chain[stage] = (nid, value)  # repaired for the
                    # session's remaining steps too
                    log.info(
                        "session %s: stage-%d hop died (%s); rescued to "
                        "advertised KV holder %s", session_id, stage, e, nid,
                    )
                    result = await self._hop(
                        self._addr(value), stage, session_id, payload
                    )
                if "logits" in result:
                    return np.asarray(result["logits"])[0]
                payload = self._next_payload(result, payload)
            raise RuntimeError("chain ended without logits — incomplete chain?")

        # first pass: walk with the planner, replanning ahead of the agent
        planner = plan.planner
        assert planner is not None
        # plan.chain aliases the walk-in-progress so _end_session can clean
        # the stages a FAILED first pass already touched
        walked = plan.chain = []
        from inferd_tpu.control.path_finder import NoNodeForStage

        for stage in range(self.num_stages):
            try:
                planner.refresh(self._snapshot())
                nxt = planner.chain()[0]  # (stage, node_id, value) — next hop
            except NoNodeForStage as e:
                raise self._routing_unavailable(e) from e
            if nxt[0] != stage:
                raise RuntimeError(f"planner skipped stage {stage}: {nxt}")
            _, nid, value = nxt
            result = await self._hop(self._addr(value), stage, session_id, payload)
            walked.append((nid, value))
            planner.advance(stage, nid)
            if self.hop_hook is not None:
                await self.hop_hook(session_id, stage)
            if "logits" in result:
                if stage != self.num_stages - 1:
                    raise RuntimeError(
                        f"stage {stage} returned logits before the last stage"
                    )
                plan.chain = walked
                plan.committed = True
                plan.stats = dict(planner.stats)
                plan.planner = None  # frozen: drop the planner state
                return np.asarray(result["logits"])[0]
            payload = self._next_payload(result, payload)
        raise RuntimeError("walked every stage without logits")

    @staticmethod
    def _hop_failure_rescuable(e: Exception) -> bool:
        """Which committed-chain hop failures are worth a holder lookup:
        transport-level death (connection refused/reset, timeout, garbage
        body) and retryable server errors, plus 409 unknown_session — the
        replica is alive but LOST the KV (restart, eviction); another
        replica may hold the handed-off copy."""
        import aiohttp

        if isinstance(e, (OSError, asyncio.TimeoutError, aiohttp.ClientError,
                          ValueError)):
            return True
        if isinstance(e, ServerError):
            # retryable covers 5xx and code "session_state" (the replica is
            # alive but lost this session's KV — exactly the case a
            # handed-off copy elsewhere fixes); deterministic 4xx
            # (overflow, malformed) stay fatal
            return e.retryable
        return False

    def _find_session_holder(
        self, session_id: str, stage: int, exclude: str, cause: Exception
    ) -> Tuple[str, Dict[str, Any]]:
        """Live same-stage replica advertising this session's KV in the
        gossip view (the client-side mirror of runtime.node's
        _gossip_session_holder). Raises a retryable 503 when none is
        advertised — generate_ids then restarts the session."""
        from inferd_tpu.control.dht import sess_hash

        h = sess_hash(session_id)
        for nid, value in self.dht.get_stage(stage).items():
            if nid != exclude and h in (value.get("sess") or ()):
                return nid, value
        raise ServerError(
            f"stage-{stage} hop failed ({cause}) and no replica advertises "
            f"session KV — restarting the session", 503, code="no_holder",
        ) from cause

    @staticmethod
    def _next_payload(result: Dict[str, Any], prev: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "hidden": result["hidden"],
            "start_pos": int(result.get("start_pos", prev["start_pos"])),
            "real_len": int(result.get("real_len", prev["real_len"])),
        }

    async def _end_session(self, session_id: str) -> None:
        plan = self._plans.pop(session_id, None)
        if plan is None or not plan.chain:
            return
        await asyncio.gather(
            *(
                self._post(
                    self._addr(value),
                    "/end_session",
                    {"session_id": session_id, "stage": stage, "relay": False},
                )
                for stage, (_, value) in enumerate(plan.chain)
            ),
            return_exceptions=True,  # best effort: servers TTL-sweep orphans
        )

    async def _fork_session(
        self, new_session_id: str, parent_session_id: str, prefix_len: int
    ) -> bool:
        """Fork on the PARENT's committed chain (that's where its KV lives);
        the child inherits the same chain."""
        parent = self._plans.get(parent_session_id)
        if parent is None or not parent.committed:
            return False
        results = await asyncio.gather(
            *(
                self._post(
                    self._addr(value),
                    "/fork_session",
                    {
                        "session_id": new_session_id,
                        "parent_session_id": parent_session_id,
                        "prefix_len": prefix_len,
                        "stage": stage,
                        "relay": False,
                    },
                )
                for stage, (_, value) in enumerate(parent.chain)
            ),
            return_exceptions=True,
        )
        if any(isinstance(r, dict) and not r.get("ok") for r in results):
            return False
        for r in results:
            if isinstance(r, BaseException):
                raise r
        child = _SessionPlan(None)
        child.chain = list(parent.chain)
        child.committed = True
        self._plans[new_session_id] = child
        return True

    # kept public: tests and operators end sessions explicitly
    async def end_session(self, session_id: str) -> None:
        await self._end_session(session_id)
