"""Scenario catalog (docs/CONTROL.md §5 documents each one's story).

Every entry is a builder returning a plain config dict for
`sim.scenario.run_scenario`; committed fixtures (tests/data/sim/) bind a
catalog name + seed + gates. Scenarios deliberately target one
control-plane behavior each — a failing gate should point at a policy,
not at a soup.
"""

from __future__ import annotations

from typing import Any, Callable, Dict


def _hysteresis() -> Dict[str, Any]:
    """Regression for the min-load float-equality deadlock
    (control/balance rebalance_once): stage 0 (one replica, cap 7) sits
    at ratio 2/7=0.2857 — the EXACT min — but can never migrate (single
    replica); stage 1 (three replicas, cap 8) sits at 7/24=0.2917, within
    the 0.01 tolerance of min but not equal to it; stage 2 is hot. The
    pre-fix equality check left NOBODY eligible; the tolerance-based min
    check lets a stage-1 replica close the gap."""
    return {
        "name": "hysteresis",
        "stages": 3,
        "replicas": [1, 3, 2],
        "caps": [7, 8, 8],
        "duration_s": 30.0,
        "balancer": {"period_s": 5.0, "min_dwell_s": 60.0},
        "workload": {"arrival_per_s": 0.0},
        "events": [
            {"t": 0.1, "op": "set_load", "node": "s0r000", "load": 2},
            {"t": 0.1, "op": "set_load", "node": "s1r000", "load": 3},
            {"t": 0.1, "op": "set_load", "node": "s1r001", "load": 2},
            {"t": 0.1, "op": "set_load", "node": "s1r002", "load": 2},
            {"t": 0.1, "op": "set_stage_load", "stage": 2, "load": 8},
        ],
    }


def _adopt_race() -> Dict[str, Any]:
    """Empty-stage adoption under gossip lag: 50 stage-0 replicas all
    observe stage 1 die (one replica, killed without a tombstone — pure
    TTL expiry) within a gossip period of each other. The lexicographic
    min-id tie-break must produce EXACTLY ONE adoption and never abandon
    stage 0 — the pre-PR-12 rebalance sweep would pile every replica in."""
    return {
        "name": "adopt_race",
        "stages": 2,
        "replicas": [50, 1],
        "duration_s": 30.0,
        "gossip_period_s": 0.5,
        "ttl_s": 4.0,
        "net": {"latency_ms": (50.0, 200.0)},
        "balancer": {"period_s": 2.0},
        "workload": {"arrival_per_s": 0.0},
        "events": [{"t": 5.0, "op": "kill", "node": "s1r000"}],
    }


def _drain_wave() -> Dict[str, Any]:
    """Drain-wave load accounting (control/balance stage_loads): two of
    stage 1's four replicas drain while carrying heavy resident load.
    Excluding draining capacity keeps the stage's apparent ratio at its
    SERVING replicas' (idle) level, so no spurious migration chases
    capacity that is about to leave — pre-fix the inflated ratio pulled
    a stage-0 replica across."""
    return {
        "name": "drain_wave",
        "stages": 2,
        "replicas": [3, 4],
        "duration_s": 30.0,
        "drain_s": 12.0,
        "balancer": {"period_s": 4.0},
        "workload": {"arrival_per_s": 0.0},
        "events": [
            {"t": 1.0, "op": "set_load", "node": "s1r000", "load": 12},
            {"t": 1.0, "op": "set_load", "node": "s1r001", "load": 12},
            {"t": 5.0, "op": "drain", "node": "s1r000"},
            {"t": 5.5, "op": "drain", "node": "s1r001"},
        ],
    }


def _hot_stage_skew() -> Dict[str, Any]:
    """Organic rebalancing under live traffic: stage 1 has 2 replicas to
    its neighbors' 5, so per-session pipeline load runs it hot. The
    cost-aware balancer must migrate capacity in (converging, never
    oscillating) while D*-Lite keeps chains near offline-optimal."""
    return {
        "name": "hot_stage_skew",
        "stages": 3,
        "replicas": [5, 2, 5],
        "duration_s": 90.0,
        "balancer": {"period_s": 8.0},
        "workload": {
            "arrival_per_s": 4.0,
            "prompt_tokens": 96,
            "new_tokens": 24,
            "deadline_s": 25.0,
        },
    }


def _retry_storm() -> Dict[str, Any]:
    """PR 10's retry budgets replayed at fleet scale: stage 1 loses two
    of three replicas at once; the survivor saturates, sessions shed and
    die on deadlines — and the token-bucket budget must keep total
    retries BOUNDED (rate*horizon + burst) instead of multiplying the
    storm."""
    return {
        "name": "retry_storm",
        "stages": 2,
        "replicas": [3, 3],
        "cap": 6,
        "kv_blocks": 96,
        "duration_s": 60.0,
        "balancer": {"period_s": 6.0, "min_dwell_s": 20.0},
        "workload": {
            "arrival_per_s": 6.0,
            "prompt_tokens": 96,
            "new_tokens": 24,
            "deadline_s": 15.0,
        },
        "events": [{"t": 10.0, "op": "kill_stage", "stage": 1, "keep": 1}],
    }


def _zonal_failure() -> Dict[str, Any]:
    """A whole zone (2 replicas of each of 3 stages) dies mid-traffic:
    sessions on the dead replicas rescue through the routers' peer.dead
    increments, chains re-plan around the hole, goodput holds."""
    return {
        "name": "zonal_failure",
        "stages": 3,
        "replicas": [6, 6, 6],
        "zones": 3,
        "duration_s": 75.0,
        "balancer": {"period_s": 8.0},
        "workload": {
            "arrival_per_s": 3.0,
            "prompt_tokens": 96,
            "new_tokens": 24,
            "deadline_s": 25.0,
        },
        "events": [{"t": 15.0, "op": "kill_zone", "zone": 1}],
    }


def _autoscale_elastic() -> Dict[str, Any]:
    """Elastic scaling end to end: a 2x2 fleet takes sustained overload
    (load + kvfree watermark both fire), the AutoScaler provisions
    replicas (whose joins the D*-Lite planner SPLICES in incrementally),
    then scales back down once arrivals stop. Gates pin at least one up
    AND one down decision, incremental node adds, and a served-load
    floor."""
    return {
        "name": "autoscale_elastic",
        "stages": 2,
        "replicas": [2, 2],
        "cap": 4,
        "kv_blocks": 64,
        "duration_s": 100.0,
        "balancer": {"period_s": 10.0},
        "workload": {
            "arrival_per_s": 4.0,
            "arrive_until_s": 50.0,
            "prompt_tokens": 96,
            "new_tokens": 24,
            "deadline_s": 25.0,
        },
        "autoscale": {
            "period_s": 6.0,
            "provision_s": 3.0,
            "cooldown_s": 15.0,
            "load_hi": 0.7,
            "load_lo": 0.15,
            "min_replicas": 2,
        },
    }


def _gossip_partition() -> Dict[str, Any]:
    """Zone partition, then heal: gossip between zones 0 and 1 blackholes
    for 20 s. Routers keep serving from their reachable view (records
    TTL out, chains re-plan), and the fleet reconverges after the heal
    with no hung sessions."""
    return {
        "name": "gossip_partition",
        "stages": 2,
        "replicas": [4, 4],
        "zones": 2,
        "duration_s": 70.0,
        "ttl_s": 8.0,
        "workload": {
            "arrival_per_s": 2.0,
            "prompt_tokens": 64,
            "new_tokens": 16,
            "deadline_s": 20.0,
        },
        "events": [
            {"t": 15.0, "op": "partition", "zones": [0, 1], "heal_after": 20.0},
        ],
    }


def _cache_affinity() -> Dict[str, Any]:
    """Memory-plane routing rehearsal (ISSUE 13): one entry stage of 6
    replicas serving 4 shared-prefix session families under KV pressure
    (small pools — the admission watermark is LIVE, not decorative).
    With digest gossip + the AffinityProbe bonus, each family converges
    onto the replica already holding its blocks, so the fleet hit rate
    climbs toward (prompt - first-visit) levels; the affinity=False
    override is the digest-off baseline fixture pinning that min-load
    alone scatters families across replicas (lower hit rate). Gates also
    hold the watermark story: a shedding digest-holder must LOSE the
    pick, so sessions keep completing instead of herding into 503s."""
    return {
        "name": "cache_affinity",
        "stages": 1,
        "replicas": [6],
        "cap": 8,
        "kv_blocks": 32,
        "base_svc_ms": 80.0,
        "duration_s": 60.0,
        # capacity 8 keys ~ 2 of the 4 families' chains: a replica can
        # NOT hold everything, so scattered placement (the digest-off
        # baseline) keeps re-learning and evicting while affinity
        # placement converges one-family-per-replica
        "prefix_cache": {"groups": 4, "capacity": 8, "affinity": True},
        "workload": {
            "arrival_per_s": 4.0,
            "prompt_tokens": 128,
            "new_tokens": 16,
            "deadline_s": 20.0,
        },
    }


def _cache_affinity_1000() -> Dict[str, Any]:
    """The 1000-node flavor (ROADMAP 2c x 3a): 4 stages x 250 replicas,
    16 prefix families routed by digest affinity at the entry stage,
    steady traffic. Holds at scale what the small fixture holds at 6
    replicas: families converge onto digest-holders (fleet hit rate
    floor) while the admission watermark keeps winning (bounded sheds,
    zero hung). Marked slow (fixture `"slow": true`)."""
    return {
        "name": "cache_affinity_1000",
        "stages": 4,
        "replicas": 250,
        "zones": 4,
        "routers": 2,
        "duration_s": 20.0,
        "warmup_s": 10.0,
        "gossip_period_s": 2.0,
        "ttl_s": 8.0,
        "anti_entropy_every": 4,
        "quality_sample_every": 4,
        "cap": 16,
        "prefix_cache": {"groups": 16, "capacity": 16, "affinity": True},
        "workload": {
            "arrival_per_s": 6.0,
            "arrive_until_s": 14.0,
            "prompt_tokens": 64,
            "new_tokens": 16,
            "deadline_s": 8.0,
        },
    }


def _adapter_affinity() -> Dict[str, Any]:
    """Multi-tenant placement rehearsal (ISSUE 15): one entry stage of 6
    replicas serving 8 tenants' adapter sessions, each replica keeping
    only 2 adapters device-resident (LRU) — the fleet CANNOT hold every
    tenant everywhere, so placement decides whether admissions hit a
    resident adapter or pay a hot-load. With `ada` residency gossip +
    the AdapterAffinity bonus (the real routers' scoring), tenants
    converge onto replicas already holding their adapter and the
    resident-hit rate climbs; the affinity=False override is the
    residency-blind baseline fixture pinning that min-load alone keeps
    thrashing the slots (loads + evictions up, hit rate down). Gates
    also pin the serving story: zero hung sessions, goodput floor —
    a miss HOT-LOADS (bounded extra units), never rejects."""
    return {
        "name": "adapter_affinity",
        "stages": 1,
        "replicas": [6],
        "cap": 8,
        "base_svc_ms": 60.0,
        "duration_s": 60.0,
        # capacity 2 of 8 tenants per replica: blind placement misses
        # ~constantly while affinity placement pins tenant->replica
        "adapter_cache": {
            "tenants": 8, "capacity": 2, "load_units": 6.0,
            "affinity": True,
        },
        "workload": {
            "arrival_per_s": 4.0,
            "prompt_tokens": 64,
            "new_tokens": 16,
            "deadline_s": 20.0,
        },
    }


def _standby_failover() -> Dict[str, Any]:
    """Crash-tolerant sessions at fleet scale (ISSUE 14): one entry
    stage of 6 replicas under steady long-session traffic, then two
    kill-churn waves take out 3 of them with live residents. With the
    standby model on (the sim mirror of runtime/repl's async KV
    replication), each stranded session PROMOTES onto a surviving
    standby and redoes only the work past the replication frontier
    (lag_units) instead of its whole prompt+decode — gates pin
    promotions actually happening, zero hung sessions, and a goodput
    floor a full-restart fleet under the same kills would miss. The
    `standby_repl: None` override is the replication-off twin."""
    return {
        "name": "standby_failover",
        "stages": 1,
        "replicas": [6],
        "cap": 8,
        "base_svc_ms": 40.0,
        "duration_s": 50.0,
        "standby_repl": {"lag_units": 8.0},
        "workload": {
            "arrival_per_s": 4.0,
            "prompt_tokens": 256,
            "new_tokens": 64,
            "deadline_s": 30.0,
        },
        "events": [
            {"t": 8.0, "op": "kill_random", "count": 2, "tag": "crash1"},
            {"t": 10.0, "op": "join", "stage": 0, "count": 2},
            {"t": 18.0, "op": "kill_random", "count": 2, "tag": "crash2"},
            {"t": 20.0, "op": "join", "stage": 0, "count": 2},
            {"t": 28.0, "op": "kill_random", "count": 1, "tag": "crash3"},
        ],
    }


def _churn_1000() -> Dict[str, Any]:
    """The 1000-node rehearsal: 8 stages x 125 replicas across 4 zones,
    steady traffic, then 60 random deaths, 30 joins, and 10 degraded
    replicas inside a 6-second window. Gates hold the whole story at
    once: routing within 5% of offline-optimal, incremental replans far
    under build cost, bounded migrations, zero hung sessions, goodput
    floor. Marked slow (fixture `"slow": true`): minutes of wall time."""
    return {
        "name": "churn_1000",
        "stages": 8,
        "replicas": 125,
        "zones": 4,
        "routers": 2,
        "duration_s": 24.0,
        "warmup_s": 10.0,
        "gossip_period_s": 2.0,
        "ttl_s": 8.0,
        "anti_entropy_every": 4,
        "quality_sample_every": 4,
        "cap": 16,
        "balancer": {"period_s": 6.0, "min_dwell_s": 15.0},
        "workload": {
            "arrival_per_s": 6.0,
            "arrive_until_s": 16.0,
            "prompt_tokens": 64,
            "new_tokens": 16,
            "deadline_s": 8.0,
            "retry_rate_per_s": 10.0,
        },
        "events": [
            {"t": 6.0, "op": "kill_random", "count": 60, "tag": "churn"},
            {"t": 8.0, "op": "join", "stage": 1, "count": 10},
            {"t": 8.5, "op": "join", "stage": 4, "count": 10},
            {"t": 9.0, "op": "join", "stage": 6, "count": 10},
            {"t": 9.5, "op": "degrade_random", "count": 10, "factor": 5.0,
             "tag": "deg"},
        ],
    }


CATALOG: Dict[str, Callable[[], Dict[str, Any]]] = {
    "hysteresis": _hysteresis,
    "adopt_race": _adopt_race,
    "drain_wave": _drain_wave,
    "hot_stage_skew": _hot_stage_skew,
    "retry_storm": _retry_storm,
    "zonal_failure": _zonal_failure,
    "autoscale_elastic": _autoscale_elastic,
    "gossip_partition": _gossip_partition,
    "cache_affinity": _cache_affinity,
    "cache_affinity_1000": _cache_affinity_1000,
    "adapter_affinity": _adapter_affinity,
    "standby_failover": _standby_failover,
    "churn_1000": _churn_1000,
}


def scenario(name: str, overrides: Dict[str, Any] = None) -> Dict[str, Any]:
    """Catalog lookup + shallow-per-key override merge (nested dicts
    merge one level down, mirroring fleet._merge_cfg semantics)."""
    if name not in CATALOG:
        raise KeyError(
            f"unknown scenario {name!r}: want one of {sorted(CATALOG)}"
        )
    cfg = CATALOG[name]()
    for k, v in (overrides or {}).items():
        if isinstance(v, dict) and isinstance(cfg.get(k), dict):
            merged = dict(cfg[k])
            merged.update(v)
            cfg[k] = merged
        else:
            cfg[k] = v
    return cfg
