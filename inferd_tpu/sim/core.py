"""Simulator engine: virtual clock, ordered event heap, datagram network.

Everything that makes the real control plane non-deterministic is owned
here and seeded: time (a virtual clock the event heap advances), the RNG
(one root `random.Random` plus stable per-actor children), and the
network (an in-process datagram fabric with a seeded latency/loss model
that plugs into SwarmDHT's `transport` seam). The control-plane modules
under test run UNMODIFIED — they just read the injected clock and rng.

Event ordering is total: the heap orders by (virtual time, insertion
sequence), callbacks scheduled at equal times run in scheduling order,
and no wall-clock read exists anywhere on the path — which is what makes
`same seed + same scenario -> byte-identical trace` a property the tests
can assert rather than a hope.
"""

from __future__ import annotations

import heapq
import itertools
import random
from typing import Any, Callable, Dict, List, Optional, Tuple

import msgpack

#: Virtual epoch: an arbitrary fixed wall-clock-looking origin so record
#: timestamps resemble production values (and never depend on the host).
SIM_EPOCH = 1_700_000_000.0


def run_coro(coro) -> Any:
    """Drive a control-plane coroutine to completion synchronously.

    The async surfaces the sim calls (Balancer.rebalance_once,
    adopt_stage, the injected change_stage) do pure in-memory work — the
    only awaits on the path are uncontended asyncio.Lock fast paths,
    which return without suspending. A genuine suspension means real I/O
    leaked into a sim path; that is a bug, so it raises instead of
    silently blocking the virtual clock."""
    try:
        coro.send(None)
    except StopIteration as stop:
        return stop.value
    coro.close()
    raise RuntimeError(
        "sim coroutine suspended: real I/O on a simulated control path"
    )


class SimTimer:
    """Cancellation handle for a scheduled callback."""

    __slots__ = ("when", "cancelled")

    def __init__(self, when: float):
        self.when = when
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class SimLoop:
    """Virtual-clock discrete-event loop."""

    def __init__(self, seed: int, start_time: float = SIM_EPOCH):
        self.seed = int(seed)
        self.now = float(start_time)
        self.rng = random.Random(f"inferd-sim:{seed}")
        self._heap: List[Tuple[float, int, SimTimer, Callable, tuple]] = []
        self._seq = itertools.count()
        self.fired = 0

    def child_rng(self, name: str) -> random.Random:
        """Stable per-actor RNG: independent of scheduling order, fully
        determined by (seed, actor name)."""
        return random.Random(f"inferd-sim:{self.seed}:{name}")

    def time(self) -> float:
        """Injected in place of time.time()/time.monotonic(): the sim
        epoch is both (skewless fleet; skew is a latency-model concern)."""
        return self.now

    def call_at(self, when: float, fn: Callable, *args: Any) -> SimTimer:
        t = SimTimer(max(when, self.now))
        heapq.heappush(self._heap, (t.when, next(self._seq), t, fn, args))
        return t

    def call_after(self, delay: float, fn: Callable, *args: Any) -> SimTimer:
        return self.call_at(self.now + max(0.0, delay), fn, *args)

    def run_until(self, t_end: float, max_events: int = 5_000_000) -> None:
        """Advance virtual time, firing every event due up to t_end.
        `max_events` is a runaway backstop (an accidental zero-delay
        self-rescheduling loop would otherwise spin forever at one
        instant of virtual time)."""
        fired = 0
        while self._heap and self._heap[0][0] <= t_end:
            when, _, timer, fn, args = heapq.heappop(self._heap)
            if timer.cancelled:
                continue
            self.now = when
            fired += 1
            self.fired += 1
            if fired > max_events:
                raise RuntimeError(
                    f"sim exceeded {max_events} events before t={t_end}"
                )
            fn(*args)
        self.now = max(self.now, t_end)


class SimNet:
    """In-process datagram fabric behind SwarmDHT's `transport` seam.

    `sendto(src_dht, data, addr)` mirrors the UDP sendto contract: bytes
    go in (the REAL msgpack wire bytes SwarmDHT packed — serialization
    bugs stay observable), a seeded latency sample and loss roll decide
    delivery, and the destination's `_on_message` fires at the delivery
    instant with the sender's (host, port) — exactly what the UDP
    protocol adapter would have passed. Zones support partitions: a
    blocked zone pair drops every datagram between them."""

    def __init__(
        self,
        loop: SimLoop,
        latency_ms: Tuple[float, float] = (2.0, 20.0),
        drop_p: float = 0.0,
    ):
        self.loop = loop
        self.latency_ms = (float(latency_ms[0]), float(latency_ms[1]))
        self.drop_p = float(drop_p)
        self._rng = loop.child_rng("net")
        self._dhts: Dict[Tuple[str, int], Any] = {}
        self._zone: Dict[Tuple[str, int], int] = {}
        self._blocked: set = set()
        self.sent = 0
        self.delivered = 0
        self.dropped = 0
        self.bytes_sent = 0
        # parse-once memo: a fanout round sends ONE packed frame to K
        # targets; deserializing it K times was the single biggest cost
        # of a 1000-node run (4 ms per 1000-record frame). Receivers
        # only read the parsed message (merge copies what it keeps), so
        # sharing the object is safe. Keyed by the bytes themselves
        # (CPython caches bytes hashes), bounded LRU.
        self._parsed: "dict[bytes, Any]" = {}

    def register(self, dht: Any, zone: int = 0) -> None:
        self._dhts[(dht.host, dht.port)] = dht
        self._zone[(dht.host, dht.port)] = int(zone)

    def set_partition(self, zone_a: int, zone_b: int, blocked: bool = True) -> None:
        key = (min(zone_a, zone_b), max(zone_a, zone_b))
        if blocked:
            self._blocked.add(key)
        else:
            self._blocked.discard(key)

    def _latency_s(self) -> float:
        lo, hi = self.latency_ms
        return (lo + (hi - lo) * self._rng.random()) / 1e3

    def sendto(self, src_dht: Any, data: bytes, addr: Tuple[str, int]) -> None:
        self.sent += 1
        self.bytes_sent += len(data)
        dst = self._dhts.get(tuple(addr))
        src_addr = (src_dht.host, src_dht.port)
        if dst is None or not dst._started:
            self.dropped += 1
            return
        za = self._zone.get(src_addr, 0)
        zb = self._zone.get(tuple(addr), 0)
        if (min(za, zb), max(za, zb)) in self._blocked:
            self.dropped += 1
            return
        if self.drop_p > 0.0 and self._rng.random() < self.drop_p:
            self.dropped += 1
            return
        self.loop.call_after(self._latency_s(), self._deliver, data, src_addr, dst)

    def _deliver(self, data: bytes, src_addr: Tuple[str, int], dst: Any) -> None:
        if not dst._started:
            self.dropped += 1
            return
        msg = self._parsed.get(data)
        if msg is None:
            try:
                msg = msgpack.unpackb(data, raw=False)
            except Exception:
                self.dropped += 1
                return
            if len(self._parsed) >= 64:
                self._parsed.pop(next(iter(self._parsed)))
            self._parsed[data] = msg
        self.delivered += 1
        dst._on_message(msg, src_addr)
