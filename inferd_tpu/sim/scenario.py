"""Scenario running, gating, and committed-fixture checking.

A scenario is a plain config dict (inferd_tpu.sim.fleet.DEFAULTS schema,
catalog in inferd_tpu.sim.scenarios); running one yields a metrics object
plus a blake2b hash over the full event trace. A FIXTURE is a committed
JSON file (tests/data/sim/) binding {scenario, seed, gates, expect}:

  * `gates` are [path, op, value] bounds over the metrics — the scenario's
    acceptance contract (routing quality, convergence, goodput,
    incremental-replan fractions). They hold for ANY conforming change.
  * `expect` pins exact replay values (trace hash/event count, session
    counts) — the determinism contract. Same seed + same scenario + same
    control-plane code => byte-identical trace; an intentional
    control-plane change regenerates fixtures with
    `python -m inferd_tpu.sim regen <fixture>` and the diff shows exactly
    which behaviors moved.

`python -m inferd_tpu.sim --check tests/data/sim` (run.sh step 0g,
tier-1-gated via tests/test_sim.py) replays every non-slow fixture and
enforces both blocks.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from inferd_tpu.sim.fleet import Fleet

_OPS = {
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


def run_scenario(
    cfg: Dict[str, Any], seed: int = 0, capture_trace: bool = False
) -> Dict[str, Any]:
    """Run one scenario to completion; returns the metrics object (plus
    `trace_lines` when capture_trace — tests assert byte-identity on it)."""
    fleet = Fleet(cfg, seed)
    fleet.capture_trace = capture_trace
    metrics = fleet.run()
    if capture_trace:
        metrics["trace_lines"] = fleet.trace_lines
    return metrics


def metric_path(metrics: Dict[str, Any], path: str) -> Any:
    """Dotted lookup: "sessions.ok", "planner.replan_frac",
    "fleet.replicas_final.1" (list index), "balance.migrate_dst.2"."""
    cur: Any = metrics
    for part in path.split("."):
        if isinstance(cur, dict):
            if part not in cur:
                return None
            cur = cur[part]
        elif isinstance(cur, (list, tuple)):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
    return cur


def check_gates(
    metrics: Dict[str, Any], gates: Sequence[Sequence[Any]]
) -> List[str]:
    """Failures (empty = pass) for [path, op, value] bound triples. A
    missing metric FAILS its gate — a gate over a signal that stopped
    existing is a regression, not a skip."""
    failures: List[str] = []
    for gate in gates:
        path, op, want = gate[0], gate[1], gate[2]
        if op not in _OPS:
            failures.append(f"{path}: unknown op {op!r}")
            continue
        got = metric_path(metrics, path)
        if not isinstance(got, (int, float)) or isinstance(got, bool):
            failures.append(f"{path} {op} {want}: metric missing (got {got!r})")
            continue
        if not _OPS[op](got, want):
            failures.append(f"{path} {op} {want}: observed {got}")
    return failures


def _values_match(got: Any, want: Any, rel_tol: float = 1e-9) -> bool:
    if isinstance(want, float) or isinstance(got, float):
        if not isinstance(got, (int, float)) or not isinstance(want, (int, float)):
            return False
        return math.isclose(float(got), float(want), rel_tol=rel_tol, abs_tol=1e-12)
    return got == want


def check_expect(
    metrics: Dict[str, Any], expect: Dict[str, Any]
) -> List[str]:
    """Failures for the exact-replay block: {dotted path: value}."""
    failures: List[str] = []
    for path, want in sorted(expect.items()):
        got = metric_path(metrics, path)
        if not _values_match(got, want):
            failures.append(f"{path}: expected {want!r}, observed {got!r}")
    return failures


def load_fixture(path: str) -> Dict[str, Any]:
    with open(path) as f:
        fx = json.load(f)
    if not isinstance(fx, dict) or "scenario" not in fx:
        raise ValueError(f"{path}: fixture needs a 'scenario' key")
    return fx


def resolve_fixture_cfg(fx: Dict[str, Any]) -> Dict[str, Any]:
    """Fixture scenario = catalog name (plus optional overrides) or an
    inline config dict."""
    from inferd_tpu.sim.scenarios import scenario as catalog_scenario

    sc = fx["scenario"]
    if isinstance(sc, str):
        return catalog_scenario(sc, fx.get("overrides") or {})
    if isinstance(sc, dict):
        cfg = dict(sc)
        for k, v in (fx.get("overrides") or {}).items():
            cfg[k] = v
        return cfg
    raise ValueError(f"bad fixture scenario: {sc!r}")


def check_fixture(path: str) -> Tuple[bool, List[str], Dict[str, Any]]:
    """Replay one fixture: (ok, failure messages, fresh metrics)."""
    fx = load_fixture(path)
    cfg = resolve_fixture_cfg(fx)
    metrics = run_scenario(cfg, seed=int(fx.get("seed", 0)))
    failures = check_gates(metrics, fx.get("gates") or [])
    failures += check_expect(metrics, fx.get("expect") or {})
    return not failures, failures, metrics


def fixture_paths(root: str, include_slow: bool = False) -> List[str]:
    """Committed fixture files under `root`, sorted; fixtures flagged
    `"slow": true` (the 1000-node sweeps) only with include_slow."""
    out = []
    for name in sorted(os.listdir(root)):
        if not name.endswith(".json"):
            continue
        full = os.path.join(root, name)
        try:
            fx = load_fixture(full)
        except (ValueError, OSError):
            out.append(full)  # let check_fixture surface the error
            continue
        if fx.get("slow") and not include_slow:
            continue
        out.append(full)
    return out


def check_dir(
    root: str, include_slow: bool = False, verbose: bool = True
) -> bool:
    """run.sh step-0g entry: replay every (non-slow) fixture, print one
    verdict line each, return overall pass. Zero fixtures = fail (an
    empty directory must not read as a green check)."""
    paths = fixture_paths(root, include_slow)
    if not paths:
        print(f"sim check: no fixtures under {root}")
        return False
    ok_all = True
    for path in paths:
        try:
            ok, failures, metrics = check_fixture(path)
        except Exception as e:  # a broken fixture is a failure, not a crash
            ok, failures, metrics = False, [f"error: {e}"], {}
        ok_all &= ok
        if verbose:
            name = os.path.basename(path)
            gp = metrics.get("goodput_ratio")
            summary = (
                f"goodput={gp}" if gp is not None
                else f"events={metrics.get('trace', {}).get('events')}"
            )
            print(f"  {'PASS' if ok else 'FAIL'} {name} ({summary})")
            for msg in failures:
                print(f"       {msg}")
    return ok_all


def regen_fixture(path: str) -> Dict[str, Any]:
    """Re-run a fixture's scenario and rewrite its `expect` block in
    place (gates are authored, never regenerated). Dev tool for landing
    intentional control-plane changes."""
    fx = load_fixture(path)
    cfg = resolve_fixture_cfg(fx)
    metrics = run_scenario(cfg, seed=int(fx.get("seed", 0)))
    expect_keys = list(fx.get("expect") or _DEFAULT_EXPECT_KEYS)
    fx["expect"] = {}
    for key in sorted(expect_keys):
        val = metric_path(metrics, key)
        if val is not None:
            fx["expect"][key] = val
    with open(path, "w") as f:
        json.dump(fx, f, indent=1, sort_keys=True)
        f.write("\n")
    return fx


#: expect block for fresh fixtures: the determinism pins (trace identity)
#: plus the headline outcomes a silent behavior change would move.
_DEFAULT_EXPECT_KEYS = (
    "trace.hash",
    "trace.events",
    "sessions.arrived",
    "sessions.ok",
    "goodput_tokens",
    "balance.migrations",
)


def new_fixture(
    path: str,
    scenario_name: str,
    seed: int,
    gates: Sequence[Sequence[Any]],
    overrides: Optional[Dict[str, Any]] = None,
    slow: bool = False,
) -> Dict[str, Any]:
    """Author a fixture file: run the catalog scenario, pin the default
    expect keys, write JSON."""
    fx: Dict[str, Any] = {
        "scenario": scenario_name,
        "seed": int(seed),
        "gates": [list(g) for g in gates],
        "expect": {k: None for k in _DEFAULT_EXPECT_KEYS},
    }
    if overrides:
        fx["overrides"] = overrides
    if slow:
        fx["slow"] = True
    with open(path, "w") as f:
        json.dump(fx, f, indent=1, sort_keys=True)
        f.write("\n")
    return regen_fixture(path)
