"""Virtual fleet: replicas, routers, workload — real control code inside.

A `SimReplica` owns a REAL `SwarmDHT` (over the in-process SimNet
transport) and a REAL `Balancer`; a `SimRouter` owns a real `PathFinder`
whose long-lived D*-Lite `SwarmChainPlanner` replans incrementally as
gossip drifts; the optional controller runs the real `AutoScaler`; retry
pacing draws from the real `utils.retry` budgets. What the simulator
models — service times, KV block pools, wire latency, churn — is the
ENVIRONMENT those components act on; every decision under test
(merge/TTL/anti-entropy, migrate/adopt, plan/replan, scale) is
production code.

Load/latency model (deliberately simple, documented in docs/CONTROL.md):
a replica's per-step service time is `base_svc_ms * degrade * (1 +
load/cap)`; a session occupies one load unit and `blocks` KV blocks on
every replica of its chain for its whole duration; session duration is
(prefill-chunks + new tokens) x the chain's per-step latency sampled at
admission. Simple, but it closes the loop that matters: load shifts
gossip, gossip shifts routing and balancing, and those shift load.
"""

from __future__ import annotations

import heapq as _heapq
import json
import math
from collections import OrderedDict, defaultdict, deque
from hashlib import blake2b
from typing import Any, Dict, List, Optional, Tuple

from inferd_tpu.control import balance as balancelib
from inferd_tpu.control import dstar as dstarlib
from inferd_tpu.control.autoscale import Action, AutoScaler, AutoscaleConfig
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.control.path_finder import NoNodeForStage, PathFinder
from inferd_tpu.obs import canary as canarylib
from inferd_tpu.sim.core import SIM_EPOCH, SimLoop, SimNet, run_coro
from inferd_tpu.utils import retry as retrylib

BLOCK_TOKENS = 32  # KV block granularity (mirrors core.cache defaults)

DEFAULTS: Dict[str, Any] = {
    "stages": 2,
    "replicas": 3,            # int (per stage) or per-stage list
    "zones": 1,
    "routers": 1,
    "duration_s": 60.0,
    "cap": 8,
    "base_svc_ms": 20.0,
    "kv_blocks": 256,
    "admission_reserve": 0.05,
    "wire_ms": (1.0, 5.0),
    "net": {"latency_ms": (2.0, 20.0), "drop_p": 0.0},
    "gossip_period_s": 1.0,
    "ttl_s": 15.0,
    "fanout": 3,
    "anti_entropy_every": 1,
    "quality_sample_every": 1,
    # gossip-convergence runway before the scenario clock starts
    # (arrivals + churn events): a fresh fleet bootstraps through one
    # seed, and judging routing during its first hellos is noise
    "warmup_s": 5.0,
    "balancer": {
        "period_s": 10.0,
        "imbalance_threshold": 0.5,
        "min_load_tol": 0.01,
        "migration_cost": 0.25,
        "min_dwell_s": 30.0,
    },
    "migrate_warmup_s": 2.0,
    "drain_s": 3.0,
    "outlier_check_s": 0.0,   # 0 = off
    "workload": {
        "arrival_per_s": 2.0,
        "arrive_until_s": None,   # default: duration - deadline
        "prompt_tokens": 128,
        "new_tokens": 32,
        "deadline_s": 20.0,
        "max_attempts": 8,
        "retry_base_s": 0.25,
        "retry_cap_s": 4.0,
        "retry_rate_per_s": 5.0,
        "retry_burst": 32,
    },
    "autoscale": None,        # AutoscaleConfig kwargs + {"period_s", "provision_s"}
    # memory-plane model (ROADMAP 2c / ISSUE 13): None = off (existing
    # scenarios' gossip and traces stay byte-identical). A dict enables
    # per-ENTRY-replica prefix caches driven by the SAME `pfx` digest
    # field and core.prefix.AffinityProbe scoring the real routers use:
    #   {"groups": N,        # distinct shared prompt prefixes offered
    #    "capacity": K,      # digest keys a replica retains (LRU)
    #    "affinity": bool}   # routers pass the probe (False = the
    #                        # digest-off baseline the fixtures compare)
    "prefix_cache": None,
    # multi-tenant adapter model (ISSUE 15): None = off (existing
    # scenarios' gossip and traces stay byte-identical). A dict enables
    # per-ENTRY-replica resident-adapter sets driven by the SAME `ada`
    # field and runtime/adapters.AdapterAffinity scoring the real
    # routers use:
    #   {"tenants": N,       # distinct tenant adapters in play
    #    "capacity": K,      # adapters a replica keeps resident (LRU)
    #    "load_units": U,    # hot-load cost of a cache miss, work units
    #    "affinity": bool}   # routers pass the adapter affinity (False
    #                        # = the residency-blind baseline fixtures)
    "adapter_cache": None,
    # crash-tolerance model (ISSUE 14 — async standby KV replication):
    # None = off (existing scenarios' traces stay byte-identical; no
    # extra rng draws even when on — the standby pick is deterministic).
    # A dict enables entry-stage standby promotion: a session whose
    # ENTRY replica is killed resumes on a surviving same-stage standby,
    # redoing only the work past the replication frontier instead of
    # the whole prompt+decode — the sim mirror of runtime/repl:
    #   {"lag_units": L}     # work units past the frontier at the kill
    #                        # (the RPO: tick interval + partial block)
    "standby_repl": None,
}


def _merge_cfg(base: Dict[str, Any], over: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge_cfg(out[k], v)
        else:
            out[k] = v
    return out


def dijkstra_chain_cost(
    snapshot: Dict[int, Dict[str, Dict[str, Any]]], num_stages: int
) -> float:
    """Offline-optimal whole-chain cost over a snapshot: a from-scratch
    Dijkstra on the same layered graph / node_cost the D*-Lite planner
    uses — the router-quality yardstick (chosen cost / this <= gate)."""
    g = dstarlib.build_layered_graph(snapshot, 0, num_stages)
    dist = {dstarlib.START: 0.0}
    pq: List[Tuple[float, int, Any]] = [(0.0, 0, dstarlib.START)]
    seq = 1
    seen = set()
    while pq:
        d, _, u = _heapq.heappop(pq)
        if u in seen:
            continue
        seen.add(u)
        if u == dstarlib.GOAL:
            return d
        for v, c in g.succ(u):
            nd = d + c
            if nd < dist.get(v, math.inf):
                dist[v] = nd
                _heapq.heappush(pq, (nd, seq, v))
                seq += 1
    return math.inf


class Session:
    __slots__ = (
        "sid", "t_arrive", "deadline", "prompt", "tokens", "blocks",
        "attempts", "done", "chain", "timer", "router", "group",
        "t_route", "step_ms", "units", "resume_units", "resume_node",
        "tenant",
    )

    def __init__(self, sid, t_arrive, deadline, prompt, tokens, group=0,
                 tenant=None):
        self.sid = sid
        self.t_arrive = t_arrive
        self.deadline = deadline
        self.prompt = prompt
        self.tokens = tokens
        self.blocks = max(1, -(-(prompt + tokens) // BLOCK_TOKENS))
        self.attempts = 0
        self.done = False
        self.chain: List[str] = []
        self.timer = None
        self.router: Optional["SimRouter"] = None
        # shared-prefix family (memory-plane model): sessions of one
        # group start with the same synthetic prompt prefix
        self.group = group
        # tenant adapter (multi-tenant model): the session decodes with
        # this named adapter; None = the base model
        self.tenant = tenant
        # crash-tolerance model (standby_repl): progress bookkeeping for
        # the promotion math — t_route/step_ms/units stamp the LAST
        # routing; resume_units/resume_node carry a standby promotion
        # into the next attempt (work already replicated there)
        self.t_route = 0.0
        self.step_ms = 0.0
        self.units = 0.0
        self.resume_units = 0.0
        self.resume_node: Optional[str] = None


class SimReplica:
    """One virtual serving replica wrapping a real SwarmDHT + Balancer."""

    def __init__(self, fleet: "Fleet", name: str, stage: int, zone: int):
        cfg = fleet.cfg
        self.fleet = fleet
        self.name = name
        self.stage = stage
        self.zone = zone
        caps = cfg.get("caps")  # optional per-stage capacity list
        self.cap = int(caps[stage]) if caps else int(cfg["cap"])
        self.base_svc_ms = float(cfg["base_svc_ms"])
        self.degrade = 1.0
        self.kv_total = int(cfg["kv_blocks"])
        self.kv_free = self.kv_total
        self.reserve = max(1, int(cfg["admission_reserve"] * self.kv_total))
        self.static_load = 0
        self.sessions: Dict[str, Session] = {}
        self.alive = True
        self.draining = False
        self.outlier = False
        self.warm_until = -math.inf
        self.migrations = 0
        self.rng = fleet.loop.child_rng(f"replica:{name}")
        self._hops: deque = deque(maxlen=256)       # (t, latency_ms)
        self._sli: deque = deque(maxlen=1024)       # (t, ok)
        # memory-plane model (fleet.prefix_cfg): truncated prefix keys
        # this replica "holds" (LRU; the sim mirror of core.cache
        # BlockPool.digest_keys), gossiped as the same `pfx` field the
        # real node announces
        self.pfx: "OrderedDict[str, None]" = OrderedDict()
        # multi-tenant model (fleet.adapter_cfg): resident adapter names
        # (LRU; the sim mirror of runtime/adapters.AdapterRegistry),
        # gossiped as the same `ada` field the real node announces
        self.ada: "OrderedDict[str, None]" = OrderedDict()
        host, port = fleet.alloc_addr()
        self.dht = SwarmDHT(
            name, port,
            bootstrap=fleet.bootstrap_for(name),
            ttl_s=cfg["ttl_s"], gossip_period_s=cfg["gossip_period_s"],
            host=host, clock=fleet.loop.time,
            rng=fleet.loop.child_rng(f"dht:{name}"),
            transport=fleet.net, fanout=cfg["fanout"],
            anti_entropy_every=cfg["anti_entropy_every"],
        )
        bal = cfg["balancer"]
        self.balancer = balancelib.Balancer(
            self.dht, fleet.num_stages,
            get_own_stage=lambda: self.stage,
            change_stage=self._change_stage,
            period_s=bal["period_s"],
            imbalance_threshold=bal["imbalance_threshold"],
            min_load_tol=bal["min_load_tol"],
            migration_cost=bal["migration_cost"],
            min_dwell_s=bal["min_dwell_s"],
            on_event=self._on_balance_event,
            clock=fleet.loop.time,
            rng=fleet.loop.child_rng(f"bal:{name}"),
        )

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        loop = self.fleet.loop
        self.fleet.net.register(self.dht, self.zone)
        self.dht.start_local()
        self.announce(urgent=True)
        period = self.dht.gossip_period_s
        loop.call_after(self.rng.random() * period, self._gossip_tick)
        bal_period = self.balancer.period_s
        loop.call_after(
            bal_period * (0.75 + 0.5 * self.rng.random()), self._balance_tick
        )
        if self.fleet.cfg["outlier_check_s"]:
            loop.call_after(
                self.fleet.cfg["outlier_check_s"] * (0.5 + self.rng.random()),
                self._outlier_tick,
            )

    def _gossip_tick(self) -> None:
        if not self.alive:
            return
        # keep the gossiped record's load/telemetry current before the
        # fanout push (the node's tsdb tick does the same re-announce)
        self.announce(urgent=False)
        self.dht.gossip_tick()
        self.fleet.loop.call_after(self.dht.gossip_period_s, self._gossip_tick)

    def _balance_tick(self) -> None:
        if self.alive and not self.draining:
            run_coro(self.balancer.rebalance_once())
        if self.alive:
            self.fleet.loop.call_after(
                self.balancer.period_s * (0.75 + 0.5 * self.rng.random()),
                self._balance_tick,
            )

    def _outlier_tick(self) -> None:
        if not self.alive:
            return
        stage_map = {
            nid: dict(rec)
            for nid, rec in self.dht.get_stage(self.stage).items()
        }
        own = stage_map.setdefault(self.name, {})
        p99 = self.hop_p99_ms()
        if p99 is not None:
            own["hop_p99_ms"] = p99
        info = canarylib.detect_outliers(stage_map).get(self.name)
        was = self.outlier
        self.outlier = info is not None
        if self.outlier != was:
            self.fleet.trace(
                "replica.outlier" if self.outlier else "replica.outlier_cleared",
                node=self.name, stage=self.stage,
            )
            self.announce(urgent=True)
        self.fleet.loop.call_after(
            self.fleet.cfg["outlier_check_s"], self._outlier_tick
        )

    # ------------------------------------------------------------- behavior

    @property
    def load(self) -> int:
        return len(self.sessions) + self.static_load

    def svc_ms(self) -> float:
        return self.base_svc_ms * self.degrade * (1.0 + self.load / self.cap)

    def hop_p99_ms(self, window_s: float = 60.0) -> Optional[float]:
        now = self.fleet.loop.now
        vals = sorted(ms for t, ms in self._hops if now - t <= window_s)
        if not vals:
            return None
        return round(vals[min(len(vals) - 1, int(0.99 * len(vals)))], 3)

    def burn(self, window_s: float = 60.0, objective: float = 99.9) -> Optional[float]:
        now = self.fleet.loop.now
        oks = [ok for t, ok in self._sli if now - t <= window_s]
        if not oks:
            return None
        bad = sum(1 for ok in oks if not ok)
        return round((bad / len(oks)) / (1.0 - objective / 100.0), 2)

    def announce(self, urgent: bool = True) -> None:
        if not self.alive:
            return
        v: Dict[str, Any] = {
            "stage": self.stage, "load": self.load, "cap": self.cap,
            "host": self.dht.host, "port": self.dht.port,
        }
        p99 = self.hop_p99_ms()
        if p99 is not None:
            v["hop_p99_ms"] = p99
        if self.kv_total:
            v["kvfree"] = round(self.kv_free / self.kv_total, 4)
        b = self.burn()
        if b is not None:
            v["burn"] = b
        if self.draining:
            v["draining"] = 1
        if self.outlier:
            v["outlier"] = 1
        if self.fleet.prefix_cfg and self.stage == 0:
            # memory-plane gossip, mirroring runtime/node.announce:
            # the digest (MRU slice, same wire shape as
            # core.prefix.make_digest) + the admission-watermark flag
            # routers suppress the affinity bonus on. Gated on the model
            # so every pre-existing scenario's gossip stays byte-exact.
            if self.pfx:
                from inferd_tpu.core import prefix as prefixlib

                v["pfx"] = {
                    "bs": BLOCK_TOKENS,
                    "k": list(self.pfx)[-prefixlib.DIGEST_GOSSIP_KEYS:],
                }
        if self.fleet.adapter_cfg and self.stage == 0:
            # multi-tenant gossip, mirroring runtime/node.announce: the
            # resident-adapter list routers score AdapterAffinity
            # against — present even when EMPTY (key presence is the
            # capability marker, exactly like the real node). Gated on
            # the model so every pre-existing scenario's gossip stays
            # byte-exact.
            from inferd_tpu.runtime.adapters import ADA_GOSSIP_MAX

            v["ada"] = list(self.ada)[-ADA_GOSSIP_MAX:]
        if (
            (self.fleet.prefix_cfg or self.fleet.adapter_cfg)
            and self.stage == 0 and self.kv_free <= self.reserve
        ):
            # ONE admission-watermark flag for both memory-plane models
            # (the real node's shed is independent of adapter residency
            # — a watermarked replica with an empty registry must still
            # shed the affinity bonus)
            v["shed"] = 1
        self.dht.announce(v, urgent=urgent)

    def admit_check(self, blocks: int) -> Optional[str]:
        if self.draining:
            return "draining"
        if self.kv_free - blocks < self.reserve:
            return "busy"
        return None

    # --------------------------------------------------- memory-plane model

    def cache_depth(self, keys: List[str]) -> int:
        """Deepest held key index + 1 over a prompt's truncated chain
        keys — chained keys mean the deepest match names the whole
        covered prefix (the sim mirror of BlockPool.map_prefix)."""
        depth = 0
        for j, k in enumerate(keys):
            if k in self.pfx:
                depth = j + 1
        return depth

    def cache_learn(self, keys: List[str], capacity: int) -> None:
        """Register a completed prefill's keys (MRU refresh), evicting
        LRU beyond `capacity` — evictions book the fleet's
        prefix_evictions counter, the sim face of `prefix.evict`."""
        for k in keys:
            if k in self.pfx:
                self.pfx.move_to_end(k)
            else:
                self.pfx[k] = None
        while len(self.pfx) > capacity:
            self.pfx.popitem(last=False)
            self.fleet.m["prefix_evictions"] += 1

    def attach(self, sess: Session) -> None:
        self.sessions[sess.sid] = sess
        self.kv_free -= sess.blocks
        self.announce(urgent=False)

    def release(self, sess: Session) -> None:
        if self.sessions.pop(sess.sid, None) is None:
            return
        self.kv_free += sess.blocks
        if self.alive:
            self.announce(urgent=False)
            if self.draining and not self.sessions and not self.static_load:
                self._drain_finish()

    def observe(self, latency_ms: float, ok: bool) -> None:
        now = self.fleet.loop.now
        self._hops.append((now, latency_ms))
        self._sli.append((now, ok))

    # --------------------------------------------------------------- events

    async def _change_stage(self, stage: int) -> None:
        old = self.stage
        # residents are stranded by a stage swap (the executor and its KV
        # are replaced): fail them over through the router rescue path —
        # migration cost is real, which is why the balancer prices it
        for sess in list(self.sessions.values()):
            self.fleet.fail_session(sess, self, "migrate")
        self.stage = stage
        self.migrations += 1
        self.warm_until = self.fleet.loop.now + self.fleet.cfg["migrate_warmup_s"]
        self.fleet.m["migrations"] += 1
        self.fleet.m[f"migrate_dst.{stage}"] += 1
        self.fleet.trace(
            "stage.migrate", node=self.name, src=old, dst=stage
        )
        self.announce(urgent=True)

    def _on_balance_event(self, etype: str, **attrs: Any) -> None:
        self.fleet.m[f"adopt.{attrs.get('reason', 'unknown')}"] += 1
        self.fleet.trace(etype, node=self.name, **attrs)

    def kill(self) -> None:
        if not self.alive:
            return
        self.alive = False
        self.dht.kill()
        self.fleet.trace("node.kill", node=self.name, stage=self.stage)
        for sess in list(self.sessions.values()):
            self.fleet.fail_session(sess, self, "peer_dead")
        self.sessions.clear()

    def drain(self) -> None:
        if self.draining or not self.alive:
            return
        self.draining = True
        self.fleet.m["drains"] += 1
        self.fleet.trace("node.draining", node=self.name, stage=self.stage)
        self.announce(urgent=True)
        if not self.sessions and not self.static_load:
            self._drain_finish()
        else:
            # residents get a bounded settle window, then hand off
            self.fleet.loop.call_after(
                self.fleet.cfg["drain_s"], self._drain_deadline
            )

    def _drain_deadline(self) -> None:
        if self.alive and self.draining:
            for sess in list(self.sessions.values()):
                self.fleet.fail_session(sess, self, "drain_handoff")
            self._drain_finish()

    def _drain_finish(self) -> None:
        if not self.alive or not self.draining:
            return
        self.fleet.trace("node.drained", node=self.name, stage=self.stage)
        self.dht.withdraw()
        self.alive = False


class SimRouter:
    """Session entry point: a real PathFinder (D*-Lite planner inside)
    over its own gossip view, with real retry budgets."""

    def __init__(self, fleet: "Fleet", name: str):
        cfg = fleet.cfg
        self.fleet = fleet
        self.name = name
        self.rng = fleet.loop.child_rng(f"router:{name}")
        host, port = fleet.alloc_addr()
        self.dht = SwarmDHT(
            name, port, bootstrap=fleet.bootstrap_for(name),
            ttl_s=cfg["ttl_s"], gossip_period_s=cfg["gossip_period_s"],
            host=host, clock=fleet.loop.time,
            rng=fleet.loop.child_rng(f"dht:{name}"),
            transport=fleet.net, fanout=cfg["fanout"],
            anti_entropy_every=cfg["anti_entropy_every"],
        )
        self.pf = PathFinder(
            self.dht, fleet.num_stages, clock=fleet.loop.time
        )
        w = cfg["workload"]
        self.retry_budget = retrylib.RetryBudget(
            rate_per_s=w["retry_rate_per_s"], burst=w["retry_burst"],
            clock=fleet.loop.time,
        )

    def start(self) -> None:
        self.fleet.net.register(self.dht, zone=0)
        self.dht.start_local()
        period = self.dht.gossip_period_s
        self.fleet.loop.call_after(self.rng.random() * period, self._gossip_tick)

    def _gossip_tick(self) -> None:
        self.dht.gossip_tick()
        self.fleet.loop.call_after(self.dht.gossip_period_s, self._gossip_tick)

    # -------------------------------------------------------------- session

    def submit(self, sess: Session) -> None:
        sess.router = self
        self.fleet.open_sessions += 1
        self.fleet.m["arrived"] += 1
        self.fleet.trace("session.arrive", sid=sess.sid, router=self.name)
        self._attempt(sess)

    def _attempt(self, sess: Session) -> None:
        fleet = self.fleet
        if sess.done:
            return
        sess.attempts += 1
        if fleet.loop.now >= sess.deadline:
            sess.done = True
            fleet.open_sessions -= 1
            fleet.m["expired"] += 1
            fleet.trace(
                "session.expired", sid=sess.sid, attempts=sess.attempts
            )
            return
        snap = self.dht.get_all(fleet.num_stages)
        try:
            # memory-plane + multi-tenant routing: the prompt's
            # AffinityProbe and/or the tenant's AdapterAffinity (None
            # when the models are off or the scenario pins
            # affinity=False — the blind baselines) ride into the REAL
            # router, which applies the bounded affinity bonus to the
            # entry pick (runtime/adapters.combine_affinity caps the
            # composition at one bonus)
            from inferd_tpu.runtime.adapters import combine_affinity

            chain = self.pf.find_best_chain(
                0, affinity=combine_affinity(
                    fleet.affinity_probe(sess),
                    fleet.adapter_affinity(sess),
                )
            )
        except NoNodeForStage as e:
            fleet.m["route_fail"] += 1
            fleet.trace(
                "route.fail", sid=sess.sid, error=str(e)[:60]
            )
            self._retry(sess, "no_chain")
            return
        reps = [fleet.replicas.get(nid) for nid, _ in chain]
        stale = [
            nid for (nid, _), r in zip(chain, reps)
            if r is None or not r.alive
        ]
        if stale:
            # gossip hasn't TTL'd the corpse yet: the relay would observe
            # transport death — fold it into the planner NOW (peer.dead
            # increment) and retry
            for nid in stale:
                self.pf.note_peer_dead(nid)
            fleet.m["route_stale"] += 1
            self._retry(sess, "stale")
            return
        self._sample_quality(snap, chain)
        if fleet.standby_cfg and sess.resume_units > 0:
            # standby promotion (crash-tolerance model): the session's
            # replicated prefix lives on resume_node — route THROUGH it
            # (the entry stage holds the prompt KV) or, if the standby
            # died too, fall back to a full redo. Substituted AFTER the
            # quality sample: the promotion is a rescue constraint, not
            # a router choice to judge against offline-optimal.
            rb = fleet.replicas.get(sess.resume_node or "")
            if (
                rb is not None and rb.alive and not rb.draining
                and rb.stage == 0
            ):
                reps[0] = rb
            else:
                fleet.m["standby_stale"] += 1
                fleet.trace(
                    "standby.stale", sid=sess.sid,
                    node=sess.resume_node or "?",
                )
                sess.resume_units = 0.0
                sess.resume_node = None
        shed_code = None
        shed_node = None
        for r in reps:
            shed_code = r.admit_check(sess.blocks)
            if shed_code:
                shed_node = r.name
                break
        if shed_code:
            fleet.m["shed"] += 1
            fleet.trace(
                "session.shed", sid=sess.sid, node=shed_node, code=shed_code
            )
            self._retry(sess, shed_code)
            return
        step_ms = 0.0
        wire_lo, wire_hi = fleet.cfg["wire_ms"]
        for r in reps:
            warm_ms = max(0.0, r.warm_until - fleet.loop.now) * 1e3
            step_ms += r.svc_ms() + min(warm_ms, 2000.0)
            step_ms += wire_lo + (wire_hi - wire_lo) * self.rng.random()
        # memory-plane hit/miss: prefix tokens the ENTRY replica already
        # holds are skipped (fewer prefill chunks — the routing win is a
        # latency/load win, not bookkeeping); the replica then learns
        # this prompt's keys. 0 with the model off.
        hit_tokens = fleet.cache_admit(sess, reps[0])
        chunks = max(1.0, (sess.prompt - hit_tokens) / 16.0)
        units = chunks + sess.tokens
        # multi-tenant hit/miss: a session landing on a replica NOT
        # holding its adapter HOT-LOADS it (extra work units — disk +
        # host->device upload), never a reject; residency-affinity
        # routing is what makes this cost rare. 0 with the model off.
        units += fleet.adapter_admit(sess, reps[0])
        if fleet.standby_cfg and sess.resume_units > 0:
            # resume on the standby: only the work past the replication
            # frontier is redone (bounded RPO) — the promoted prefix is
            # already KV on resume_node. At least one unit always runs
            # (the resumed chunk itself recomputes).
            skipped = min(sess.resume_units, max(0.0, units - 1.0))
            units -= skipped
            fleet.m["standby_resumed_units"] += skipped
            fleet.trace(
                "standby.resume", sid=sess.sid, node=reps[0].name,
                units=round(skipped, 3),
            )
            sess.resume_units = 0.0
            sess.resume_node = None
        duration_s = units * step_ms / 1e3
        sess.t_route = fleet.loop.now
        sess.step_ms = step_ms
        sess.units = units
        for r in reps:
            r.attach(sess)
        sess.chain = [r.name for r in reps]
        fleet.trace(
            "session.route", sid=sess.sid, chain=",".join(sess.chain),
            eta_ms=round(duration_s * 1e3, 3),
        )
        # deadline enforcement (PR 10's typed 408, simulated): a route
        # that cannot finish inside the deadline stops AT the deadline —
        # resources release and the expiry books — instead of grinding
        # to a completion nobody is waiting for
        fire_in = min(duration_s, max(0.0, sess.deadline - fleet.loop.now) + 1e-3)
        sess.timer = fleet.loop.call_after(
            fire_in, self._complete, sess, step_ms
        )

    def _complete(self, sess: Session, step_ms: float) -> None:
        fleet = self.fleet
        if sess.done:
            return
        sess.done = True
        fleet.open_sessions -= 1
        ok = fleet.loop.now <= sess.deadline
        for nid in sess.chain:
            r = fleet.replicas.get(nid)
            if r is not None:
                r.release(sess)
                r.observe(step_ms, ok)
        if ok:
            fleet.m["ok"] += 1
            fleet.m["goodput_tokens"] += sess.tokens
            fleet.trace(
                "session.done", sid=sess.sid, attempts=sess.attempts,
                wall_ms=round((fleet.loop.now - sess.t_arrive) * 1e3, 3),
            )
        else:
            fleet.m["expired"] += 1
            fleet.trace(
                "session.expired", sid=sess.sid, attempts=sess.attempts
            )

    def _retry(self, sess: Session, reason: str) -> None:
        fleet = self.fleet
        w = fleet.cfg["workload"]
        if sess.attempts >= w["max_attempts"]:
            sess.done = True
            fleet.open_sessions -= 1
            fleet.m["failed"] += 1
            fleet.trace(
                "session.fail", sid=sess.sid, reason="max_attempts",
                last=reason,
            )
            return
        if not self.retry_budget.try_acquire():
            # PR 10's containment at fleet scale: a dead stage produces a
            # BOUNDED retry rate; the overflow surfaces as failures
            # instead of multiplying load
            sess.done = True
            fleet.open_sessions -= 1
            fleet.m["retry_denied"] += 1
            fleet.m["failed"] += 1
            fleet.trace("session.fail", sid=sess.sid, reason="retry_budget")
            return
        fleet.m["retries"] += 1
        delay = retrylib.backoff_delay(
            sess.attempts, base_s=w["retry_base_s"], cap_s=w["retry_cap_s"],
            rng=self.rng,
        )
        fleet.trace(
            "session.retry", sid=sess.sid, reason=reason,
            delay_ms=round(delay * 1e3, 3),
        )
        fleet.loop.call_after(delay, self._attempt, sess)

    def _sample_quality(
        self, snap: Dict[int, Dict[str, Dict[str, Any]]], chain
    ) -> None:
        fleet = self.fleet
        # the yardstick Dijkstra is O(stages x replicas^2 / stage) per
        # sample; big sweeps subsample (every Kth routing decision)
        fleet.m["route_decisions"] += 1
        every = int(fleet.cfg["quality_sample_every"])
        if every > 1 and int(fleet.m["route_decisions"]) % every != 1:
            return
        chosen = 0.0
        for s, (nid, value) in enumerate(chain):
            rec = snap.get(s, {}).get(nid, value)
            chosen += dstarlib.node_cost(rec)
        optimal = dijkstra_chain_cost(snap, fleet.num_stages)
        if not (optimal > 0.0) or math.isinf(optimal):
            return
        ratio = chosen / optimal
        fleet.m["route_samples"] += 1
        fleet._quality_sum += ratio
        fleet._quality_max = max(fleet._quality_max, ratio)


class Fleet:
    """Scenario world: builds actors, schedules churn, collects metrics."""

    def __init__(self, cfg: Dict[str, Any], seed: int):
        self.cfg = _merge_cfg(DEFAULTS, cfg or {})
        self.seed = int(seed)
        self.loop = SimLoop(seed)
        net = self.cfg["net"]
        self.net = SimNet(
            self.loop, latency_ms=tuple(net["latency_ms"]),
            drop_p=net["drop_p"],
        )
        self.num_stages = int(self.cfg["stages"])
        self.replicas: Dict[str, SimReplica] = {}
        self.routers: List[SimRouter] = []
        self.controller: Optional[AutoScaler] = None
        self._ctl_dht: Optional[SwarmDHT] = None
        self.m: Dict[str, float] = defaultdict(float)
        self._quality_sum = 0.0
        self._quality_max = 0.0
        self._hash = blake2b(digest_size=16)
        self.trace_events = 0
        self.capture_trace = False
        self.trace_lines: List[str] = []
        self._addr_seq = 0
        self._join_seq = 0
        self._seed_addr: Optional[Tuple[str, int]] = None
        # sessions not yet terminal (done/expired/failed): drives the
        # adaptive grace drain at the end of run()
        self.open_sessions = 0
        # memory-plane model (DEFAULTS["prefix_cache"]): per-group probes
        # and truncated key chains are derived ONCE from deterministic
        # synthetic prompt ids (no rng — group membership is sid modulo,
        # so enabling the model never perturbs other draws)
        self.prefix_cfg: Optional[Dict[str, Any]] = (
            dict(self.cfg["prefix_cache"])
            if self.cfg.get("prefix_cache") else None
        )
        self._group_keys: Dict[int, List[str]] = {}
        self._group_probes: Dict[int, Any] = {}
        # multi-tenant adapter model (DEFAULTS["adapter_cache"]): off =
        # None; tenant assignment is sid modulo (deterministic, no rng —
        # enabling the model never perturbs other scenarios' draws)
        self.adapter_cfg: Optional[Dict[str, Any]] = (
            dict(self.cfg["adapter_cache"])
            if self.cfg.get("adapter_cache") else None
        )
        self._tenant_affinity: Dict[str, Any] = {}
        # crash-tolerance model (DEFAULTS["standby_repl"]): off = None;
        # the standby pick is deterministic (min load, then name) so
        # enabling the model never perturbs any rng stream
        self.standby_cfg: Optional[Dict[str, Any]] = (
            dict(self.cfg["standby_repl"])
            if self.cfg.get("standby_repl") else None
        )

    # ------------------------------------------------------------- plumbing

    def alloc_addr(self) -> Tuple[str, int]:
        i = self._addr_seq
        self._addr_seq += 1
        return (f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}", 7000)

    def bootstrap_for(self, name: str) -> List[Tuple[str, int]]:
        return [self._seed_addr] if self._seed_addr else []

    # ------------------------------------------------- memory-plane model

    def _group_prompt_ids(self, group: int) -> List[int]:
        """Deterministic synthetic prompt for one shared-prefix group:
        same group => identical leading tokens (the shared system
        prompt), distinct groups => disjoint chains."""
        n = int(self.cfg["workload"]["prompt_tokens"])
        return [(group * 7919 + i * 13 + 5) % 4096 for i in range(n)]

    def group_keys(self, group: int) -> List[str]:
        """Truncated chained block keys for a group's prompt — derived
        through the REAL core.prefix pipeline (block_keys -> digest_key)
        so the sim's digests and the routers' probes can never use a
        different identity than production."""
        keys = self._group_keys.get(group)
        if keys is None:
            from inferd_tpu.core import prefix as prefixlib

            keys = [
                prefixlib.digest_key(k) for k in prefixlib.block_keys(
                    self._group_prompt_ids(group), BLOCK_TOKENS,
                    n_blocks=prefixlib.DIGEST_MAX_KEYS,
                )
            ]
            self._group_keys[group] = keys
        return keys

    def affinity_probe(self, sess: Session):
        """The session's core.prefix.AffinityProbe for router scoring, or
        None (model off / scenario pins affinity=False — the digest-off
        baseline fixtures compare against). Cached per group."""
        pc = self.prefix_cfg
        if not pc or not pc.get("affinity", True):
            return None
        probe = self._group_probes.get(sess.group)
        if probe is None:
            from inferd_tpu.core import prefix as prefixlib

            probe = prefixlib.AffinityProbe(
                self._group_prompt_ids(sess.group)
            )
            self._group_probes[sess.group] = probe
        return probe

    def adapter_affinity(self, sess: Session):
        """The session's runtime/adapters.AdapterAffinity for router
        scoring, or None (model off / no tenant / scenario pins
        affinity=False — the residency-blind baseline). Cached per
        tenant."""
        ac = self.adapter_cfg
        if not ac or sess.tenant is None or not ac.get("affinity", True):
            return None
        aff = self._tenant_affinity.get(sess.tenant)
        if aff is None:
            from inferd_tpu.runtime.adapters import AdapterAffinity

            aff = AdapterAffinity(sess.tenant)
            self._tenant_affinity[sess.tenant] = aff
        return aff

    def adapter_admit(self, sess: Session, entry: SimReplica) -> float:
        """Residency resolution at admission: 0 extra units on a HIT
        (the entry replica already holds the tenant's adapter), the
        configured hot-load cost on a MISS — which also LRU-learns the
        adapter (evicting past capacity, booking the eviction counter:
        the sim face of `adapter.load`/`adapter.evict`)."""
        ac = self.adapter_cfg
        if not ac or sess.tenant is None:
            return 0.0
        cap = max(1, int(ac.get("capacity", 4)))
        if sess.tenant in entry.ada:
            entry.ada.move_to_end(sess.tenant)
            self.m["adapter_hits"] += 1
            self.trace(
                "adapter.hit", sid=sess.sid, node=entry.name,
                tenant=sess.tenant,
            )
            return 0.0
        self.m["adapter_misses"] += 1
        entry.ada[sess.tenant] = None
        while len(entry.ada) > cap:
            entry.ada.popitem(last=False)
            self.m["adapter_evictions"] += 1
        self.trace(
            "adapter.load", sid=sess.sid, node=entry.name,
            tenant=sess.tenant,
        )
        return float(ac.get("load_units", 4.0))

    def cache_admit(self, sess: Session, entry: SimReplica) -> int:
        """Hit/miss resolution at admission: tokens of `sess`'s prompt
        the entry replica's cache covers (skipped from prefill), books
        the fleet hit/prefill counters, and teaches the replica this
        prompt's keys. 0 with the model off."""
        if not self.prefix_cfg:
            return 0
        keys = self.group_keys(sess.group)
        depth = entry.cache_depth(keys)
        hit = min(depth * BLOCK_TOKENS, max(0, sess.prompt - 1))
        if hit:
            self.m["prefix_hit_tokens"] += hit
            self.trace(
                "prefix.hit", sid=sess.sid, node=entry.name, tokens=hit
            )
        self.m["prefill_tokens"] += sess.prompt - hit
        entry.cache_learn(keys, int(self.prefix_cfg.get("capacity", 256)))
        return hit

    def trace(self, etype: str, **attrs: Any) -> None:
        line = (
            f"{self.loop.now - SIM_EPOCH:12.4f} {etype} "
            + json.dumps(attrs, sort_keys=True, separators=(",", ":"))
        )
        self._hash.update(line.encode())
        self._hash.update(b"\n")
        self.trace_events += 1
        if self.capture_trace:
            self.trace_lines.append(line)

    # ---------------------------------------------------------------- build

    def add_replica(
        self, stage: int, zone: Optional[int] = None, name: Optional[str] = None
    ) -> SimReplica:
        if name is None:
            name = f"j{self._join_seq:03d}"
            self._join_seq += 1
        if zone is None:
            zone = len(self.replicas) % int(self.cfg["zones"])
        r = SimReplica(self, name, stage, zone)
        self.replicas[name] = r
        if self._seed_addr is None:
            self._seed_addr = (r.dht.host, r.dht.port)
        r.start()
        self.trace("node.join", node=name, stage=stage, zone=zone)
        return r

    def build(self) -> None:
        reps = self.cfg["replicas"]
        counts = (
            list(reps) if isinstance(reps, (list, tuple))
            else [int(reps)] * self.num_stages
        )
        zones = int(self.cfg["zones"])
        i = 0
        for stage, n in enumerate(counts):
            for k in range(int(n)):
                self.add_replica(stage, zone=i % zones, name=f"s{stage}r{k:03d}")
                i += 1
        for ri in range(int(self.cfg["routers"])):
            router = SimRouter(self, f"router{ri}")
            self.routers.append(router)
            router.start()
        auto = self.cfg.get("autoscale")
        if auto:
            auto = dict(auto)
            self._auto_period = float(auto.pop("period_s", 15.0))
            self._auto_provision = float(auto.pop("provision_s", 5.0))
            ctl_host, ctl_port = self.alloc_addr()
            self._ctl_dht = SwarmDHT(
                "autoscaler", ctl_port, bootstrap=self.bootstrap_for("ctl"),
                ttl_s=self.cfg["ttl_s"],
                gossip_period_s=self.cfg["gossip_period_s"],
                host=ctl_host, clock=self.loop.time,
                rng=self.loop.child_rng("dht:ctl"), transport=self.net,
                fanout=self.cfg["fanout"],
                anti_entropy_every=self.cfg["anti_entropy_every"],
            )
            self.net.register(self._ctl_dht, zone=0)
            self._ctl_dht.start_local()
            self.controller = AutoScaler(
                self.num_stages, AutoscaleConfig(**auto),
                clock=self.loop.time,
                on_event=lambda etype, **attrs: self.trace(etype, **attrs),
            )
            self.loop.call_after(
                self.cfg["gossip_period_s"], self._ctl_gossip_tick
            )
            self.loop.call_after(self._auto_period, self._autoscale_tick)

    def _ctl_gossip_tick(self) -> None:
        self._ctl_dht.gossip_tick()
        self.loop.call_after(self.cfg["gossip_period_s"], self._ctl_gossip_tick)

    # ------------------------------------------------------------ autoscale

    def _autoscale_tick(self) -> None:
        snap = self._ctl_dht.get_all(self.num_stages)
        for act in self.controller.decide(snap):
            self._apply_autoscale(act)
        self.loop.call_after(self._auto_period, self._autoscale_tick)

    def _serving_of(self, stage: int) -> List[SimReplica]:
        return sorted(
            (
                r for r in self.replicas.values()
                if r.alive and not r.draining and r.stage == stage
            ),
            key=lambda r: r.name,
        )

    def _apply_autoscale(self, act: Action) -> None:
        self.m[f"autoscale.{act.kind}"] += 1
        if act.kind == "scale_up":
            for _ in range(act.count):
                self.loop.call_after(
                    self._auto_provision, self._provision, act.stage
                )
        elif act.kind == "scale_down":
            pool = self._serving_of(act.stage)
            for r in sorted(pool, key=lambda r: (r.load, r.name))[: act.count]:
                if len(self._serving_of(act.stage)) > 1:
                    r.drain()
        elif act.kind == "repartition":
            pool = self._serving_of(act.src_stage)
            if len(pool) > 1:
                mover = min(pool, key=lambda r: (r.load, r.name))
                run_coro(mover._change_stage(act.stage))

    def _provision(self, stage: int) -> None:
        self.add_replica(stage)

    # ------------------------------------------------------------- workload

    def _schedule_arrivals(self) -> None:
        w = self.cfg["workload"]
        rate = float(w["arrival_per_s"])
        if rate <= 0:
            return
        horizon = w["arrive_until_s"]
        if horizon is None:
            horizon = max(1.0, self.cfg["duration_s"] - w["deadline_s"])
        rng = self.loop.child_rng("arrivals")
        t = 0.0
        sid = 0
        while True:
            t += rng.expovariate(rate)
            if t >= horizon:
                break
            sess = Session(
                f"u{sid:05d}", self.loop.now + t, self.loop.now + t + w["deadline_s"],
                int(w["prompt_tokens"]), int(w["new_tokens"]),
                # shared-prefix family by round-robin (deterministic, no
                # rng draw — enabling the memory-plane model must not
                # shift any other scenario's random sequence)
                group=(
                    sid % max(1, int(self.prefix_cfg.get("groups", 4)))
                    if self.prefix_cfg else 0
                ),
                # tenant adapter by round-robin (deterministic, no rng
                # draw — same discipline as `group`)
                tenant=(
                    f"ada{sid % max(1, int(self.adapter_cfg.get('tenants', 4)))}"
                    if self.adapter_cfg else None
                ),
            )
            router = self.routers[sid % len(self.routers)]
            self.loop.call_at(sess.t_arrive, router.submit, sess)
            sid += 1
        self.m["offered_sessions"] = sid
        self.m["offered_tokens"] = sid * int(w["new_tokens"])

    def fail_session(self, sess: Session, at: SimReplica, reason: str) -> None:
        """A chain replica failed under a live session (death, migrate,
        drain hand-off): release everywhere, fold the death into the
        owning router's planner, and retry against the remaining
        deadline."""
        if sess.done:
            return
        if sess.timer is not None:
            sess.timer.cancel()
            sess.timer = None
        pre_chain = set(sess.chain)
        for nid in sess.chain:
            r = self.replicas.get(nid)
            if r is not None and r is not at:
                r.release(sess)
        if at.sessions.pop(sess.sid, None) is not None:
            at.kv_free += sess.blocks
        sess.chain = []
        self.m["rescues"] += 1
        self.trace(
            "session.rescue", sid=sess.sid, node=at.name, reason=reason
        )
        if self.standby_cfg and reason == "peer_dead" and at.stage == 0:
            # crash-tolerance model (the sim mirror of runtime/repl): a
            # surviving same-stage standby (anti-affinity: never a chain
            # member — the session was being SERVED there) holds the
            # session's replicated prefix up to `done - lag` work units.
            # The retry resumes there, redoing only the tail past the
            # frontier; no standby (or nothing replicated yet) books
            # standby.stale and degrades to the full redo — exactly the
            # production fallback contract.
            lag = float(self.standby_cfg.get("lag_units", 8.0))
            done = 0.0
            if sess.step_ms > 0 and sess.units > 0:
                done = min(
                    sess.units,
                    (self.loop.now - sess.t_route) * 1e3 / sess.step_ms,
                )
            standby = min(
                (
                    r for r in self._serving_of(0)
                    if r.name != at.name and r.name not in pre_chain
                ),
                key=lambda r: (r.load, r.name),
                default=None,
            )
            resume = max(0.0, done - lag)
            if standby is not None and resume > 0:
                sess.resume_units = resume
                sess.resume_node = standby.name
                self.m["standby_promotions"] += 1
                self.m["standby_promoted_units"] += resume
                self.trace(
                    "standby.promote", sid=sess.sid, node=standby.name,
                    units=round(resume, 3),
                )
            else:
                self.m["standby_stale"] += 1
                self.trace("standby.stale", sid=sess.sid, node=at.name)
        if reason == "peer_dead" and sess.router is not None:
            sess.router.pf.note_peer_dead(at.name)
        if sess.router is not None:
            sess.router._retry(sess, reason)

    # ---------------------------------------------------------------- churn

    def _apply_event(self, ev: Dict[str, Any]) -> None:
        op = ev["op"]
        self.trace("scenario.event", **{k: v for k, v in ev.items() if k != "t"})
        if op == "kill":
            r = self.replicas.get(ev["node"])
            if r is not None:
                r.kill()
        elif op == "kill_zone":
            for r in sorted(self.replicas.values(), key=lambda r: r.name):
                if r.zone == int(ev["zone"]) and r.alive:
                    r.kill()
        elif op == "kill_stage":
            keep = int(ev.get("keep", 0))
            pool = self._serving_of(int(ev["stage"]))
            for r in pool[keep:]:
                r.kill()
        elif op == "kill_random":
            rng = self.loop.child_rng(f"churn:{ev.get('tag', ev['t'])}")
            pool = sorted(
                (r for r in self.replicas.values() if r.alive),
                key=lambda r: r.name,
            )
            # never empty a stage outright: churn models independent
            # failures, zonal/stage wipes have their own ops
            by_stage: Dict[int, int] = {}
            for r in pool:
                by_stage[r.stage] = by_stage.get(r.stage, 0) + 1
            for r in rng.sample(pool, min(int(ev["count"]), len(pool))):
                if by_stage.get(r.stage, 0) > 1:
                    by_stage[r.stage] -= 1
                    r.kill()
        elif op == "join":
            for _ in range(int(ev.get("count", 1))):
                self.add_replica(int(ev["stage"]))
        elif op == "drain":
            r = self.replicas.get(ev["node"])
            if r is not None:
                r.drain()
        elif op == "drain_stage":
            pool = self._serving_of(int(ev["stage"]))
            n = int(ev.get("count", 0)) or int(len(pool) * float(ev.get("frac", 0.5)))
            for r in pool[:n]:
                if len(self._serving_of(int(ev["stage"]))) > 1:
                    r.drain()
        elif op == "degrade":
            r = self.replicas.get(ev["node"])
            if r is not None:
                r.degrade = float(ev.get("factor", 4.0))
                self.trace("node.degrade", node=r.name, factor=r.degrade)
        elif op == "degrade_random":
            rng = self.loop.child_rng(f"degrade:{ev.get('tag', ev['t'])}")
            pool = sorted(
                (r for r in self.replicas.values() if r.alive),
                key=lambda r: r.name,
            )
            for r in rng.sample(pool, min(int(ev["count"]), len(pool))):
                r.degrade = float(ev.get("factor", 4.0))
                self.trace("node.degrade", node=r.name, factor=r.degrade)
        elif op == "set_load":
            r = self.replicas.get(ev["node"])
            if r is not None:
                r.static_load = int(ev["load"])
                r.announce(urgent=False)
        elif op == "set_stage_load":
            for r in self._serving_of(int(ev["stage"])):
                r.static_load = int(ev["load"])
                r.announce(urgent=False)
        elif op == "partition":
            zones = ev["zones"]
            self.net.set_partition(int(zones[0]), int(zones[1]), True)
            if ev.get("heal_after"):
                self.loop.call_after(
                    float(ev["heal_after"]), self.net.set_partition,
                    int(zones[0]), int(zones[1]), False,
                )
        else:
            raise ValueError(f"unknown scenario op {op!r}")

    # ------------------------------------------------------------------ run

    def run(self) -> Dict[str, Any]:
        self.build()
        self.loop.run_until(self.loop.now + float(self.cfg["warmup_s"]))
        for ev in self.cfg.get("events", []):
            self.loop.call_at(self.loop.now + float(ev["t"]), self._apply_event, ev)
        self._schedule_arrivals()
        t0 = self.loop.now
        self.loop.run_until(t0 + float(self.cfg["duration_s"]))
        # grace drain: let in-flight sessions reach a terminal state
        # (done/expired/failed) so `hung` counts truly-stuck work, not
        # work the horizon merely cut off mid-retry. Adaptive: stop the
        # moment every session is terminal — a 1000-node fleet gossiping
        # through an empty grace window is pure wasted wall time
        w = self.cfg["workload"]
        grace_end = (
            t0 + float(self.cfg["duration_s"])
            + float(w["deadline_s"]) + 2.0 * float(w["retry_cap_s"]) + 1.0
        )
        while self.open_sessions > 0 and self.loop.now < grace_end:
            self.loop.run_until(min(self.loop.now + 1.0, grace_end))
        return self.finalize()

    def finalize(self) -> Dict[str, Any]:
        m = self.m
        duration = float(self.cfg["duration_s"])
        goodput = m.get("goodput_tokens", 0)
        offered = m.get("offered_tokens", 0)
        planner_stats: Dict[str, int] = {}
        for router in self.routers:
            p = router.pf.planner
            if p is None:
                continue
            for k, v in p.stats.items():
                planner_stats[k] = planner_stats.get(k, 0) + v
        builds = max(1, planner_stats.get("builds", 0))
        replans = max(
            1,
            planner_stats.get("computes", 0) - builds
        )
        mig_per_node = [r.migrations for r in self.replicas.values()]
        stage_counts = [
            len(self._serving_of(s)) for s in range(self.num_stages)
        ]
        per_build = planner_stats.get("expansions_build", 0) / builds
        per_replan = planner_stats.get("expansions_replan", 0) / replans
        sessions = {
            k: int(m.get(k, 0))
            for k in (
                "arrived", "ok", "failed", "expired", "shed",
                "retries", "retry_denied", "rescues",
                "route_fail", "route_stale",
            )
        }
        sessions["hung"] = (
            sessions["arrived"] - sessions["ok"] - sessions["failed"]
            - sessions["expired"]
        )
        out = {
            "scenario": self.cfg.get("name", ""),
            "seed": self.seed,
            "duration_s": duration,
            "sessions": sessions,
            "goodput_tokens": int(goodput),
            "goodput_per_s": round(goodput / duration, 6) if duration else 0.0,
            "goodput_ratio": round(goodput / offered, 6) if offered else None,
            "route_quality": {
                "samples": int(m.get("route_samples", 0)),
                "cost_ratio_mean": round(
                    self._quality_sum / m["route_samples"], 6
                ) if m.get("route_samples") else None,
                "cost_ratio_max": round(self._quality_max, 6)
                if m.get("route_samples") else None,
            },
            "planner": dict(
                planner_stats,
                expansions_per_build=round(per_build, 3),
                expansions_per_replan=round(per_replan, 3),
                # the incremental-replan headline: mean expansions per
                # replan as a fraction of mean expansions per from-scratch
                # build — "<< 1" is D*-Lite earning its keep
                replan_frac=round(per_replan / per_build, 4)
                if per_build > 0 else None,
            ),
            "balance": {
                "migrations": int(m.get("migrations", 0)),
                "max_migrations_per_node": max(mig_per_node, default=0),
                "adoptions": {
                    k[len("adopt."):]: int(v)
                    for k, v in sorted(m.items()) if k.startswith("adopt.")
                },
                "migrate_dst": {
                    k[len("migrate_dst."):]: int(v)
                    for k, v in sorted(m.items())
                    if k.startswith("migrate_dst.")
                },
                "drains": int(m.get("drains", 0)),
            },
            "autoscale": {
                k[len("autoscale."):]: int(v)
                for k, v in sorted(m.items()) if k.startswith("autoscale.")
            },
            "fleet": {
                "replicas_final": stage_counts,
                "replicas_total": len(self.replicas),
                "alive": sum(1 for r in self.replicas.values() if r.alive),
            },
            "net": {
                "sent": self.net.sent,
                "delivered": self.net.delivered,
                "dropped": self.net.dropped,
                "bytes_sent": self.net.bytes_sent,
            },
            "trace": {
                "events": self.trace_events,
                "hash": self._hash.hexdigest(),
            },
        }
        if self.standby_cfg:
            out["standby"] = {
                "promotions": int(m.get("standby_promotions", 0)),
                "promoted_units": round(
                    m.get("standby_promoted_units", 0.0), 3
                ),
                "resumed_units": round(
                    m.get("standby_resumed_units", 0.0), 3
                ),
                "stale": int(m.get("standby_stale", 0)),
            }
        if self.prefix_cfg:
            hit = m.get("prefix_hit_tokens", 0.0)
            pre = m.get("prefill_tokens", 0.0)
            out["cache"] = {
                "hit_tokens": int(hit),
                "prefill_tokens": int(pre),
                "hit_frac": (
                    round(hit / (hit + pre), 6) if (hit + pre) > 0 else None
                ),
                "evictions": int(m.get("prefix_evictions", 0)),
            }
        if self.adapter_cfg:
            ah = m.get("adapter_hits", 0.0)
            am = m.get("adapter_misses", 0.0)
            out["adapters"] = {
                "hits": int(ah),
                "misses": int(am),
                # resident-hit rate: the adapter-affinity routing claim —
                # sessions landing where their adapter already lives
                "hit_frac": (
                    round(ah / (ah + am), 6) if (ah + am) > 0 else None
                ),
                "evictions": int(m.get("adapter_evictions", 0)),
            }
        return out
