"""Deterministic discrete-event fleet simulator (docs/CONTROL.md §5).

Rehearses the REAL control plane — `control.dht.SwarmDHT` gossip,
`control.balance.Balancer`, `control.path_finder.PathFinder` with its
long-lived D*-Lite `SwarmChainPlanner`, `control.autoscale.AutoScaler`,
and `utils.retry`'s budgets — against thousands of virtual replicas on a
virtual clock: no sockets, no wall time, no jax. Same seed + same
scenario => byte-identical event trace and metrics.

    python -m inferd_tpu.sim run hot_stage_skew --seed 7
    python -m inferd_tpu.sim --check tests/data/sim
"""

from inferd_tpu.sim.core import SimLoop, SimNet, run_coro
from inferd_tpu.sim.fleet import Fleet, SimReplica, SimRouter
from inferd_tpu.sim.scenario import check_fixture, run_scenario
from inferd_tpu.sim.scenarios import CATALOG

__all__ = [
    "SimLoop", "SimNet", "run_coro", "Fleet", "SimReplica", "SimRouter",
    "run_scenario", "check_fixture", "CATALOG",
]
