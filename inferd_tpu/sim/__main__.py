"""Fleet-simulator CLI (docs/CONTROL.md §5).

  python -m inferd_tpu.sim --list
  python -m inferd_tpu.sim run hot_stage_skew --seed 7 [--trace out.log]
  python -m inferd_tpu.sim --check tests/data/sim [--all]
  python -m inferd_tpu.sim regen tests/data/sim/churn_1000.json

`--check` replays every committed fixture (skipping `"slow": true`
sweeps unless --all) and exits nonzero on any gate or expect failure —
run.sh step 0g runs it advisory, tests/test_sim.py gates it in tier-1.
"""

from __future__ import annotations

import argparse
import json
import sys

import inferd_tpu.sim.scenario as scenariolib
import inferd_tpu.sim.scenarios as cataloglib


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m inferd_tpu.sim", description=__doc__)
    ap.add_argument("command", nargs="?", default="",
                    help="run <name|file.json> | regen <fixture.json>")
    ap.add_argument("target", nargs="?", default="",
                    help="scenario name / fixture path")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--check", default="",
                    help="replay committed fixtures under this directory")
    ap.add_argument("--all", action="store_true",
                    help="include slow fixtures (1000-node sweeps) in --check")
    ap.add_argument("--list", action="store_true", help="list catalog scenarios")
    ap.add_argument("--trace", default="",
                    help="write the full event trace to this file (run)")
    args = ap.parse_args(argv)

    if args.list or args.command == "list":
        for name in sorted(cataloglib.CATALOG):
            doc = (cataloglib.CATALOG[name].__doc__ or "").strip().split("\n")[0]
            print(f"{name:18} {doc}")
        return 0

    if args.check:
        ok = scenariolib.check_dir(args.check, include_slow=args.all)
        print("sim check:", "PASS" if ok else "FAIL")
        return 0 if ok else 1

    if args.command == "regen":
        if not args.target:
            ap.error("regen needs a fixture path")
        fx = scenariolib.regen_fixture(args.target)
        print(json.dumps(fx["expect"], indent=1, sort_keys=True))
        return 0

    if args.command == "run":
        if not args.target:
            ap.error("run needs a scenario name or config file")
        if args.target.endswith(".json"):
            with open(args.target) as f:
                obj = json.load(f)
            # accept either a bare scenario config or a fixture file
            cfg = (
                scenariolib.resolve_fixture_cfg(obj)
                if "scenario" in obj else obj
            )
        else:
            cfg = cataloglib.scenario(args.target)
        metrics = scenariolib.run_scenario(
            cfg, seed=args.seed, capture_trace=bool(args.trace)
        )
        trace_lines = metrics.pop("trace_lines", None)
        if args.trace and trace_lines is not None:
            with open(args.trace, "w") as f:
                f.write("\n".join(trace_lines) + "\n")
            print(f"trace: {len(trace_lines)} events -> {args.trace}",
                  file=sys.stderr)
        print(json.dumps(metrics, indent=1, sort_keys=True))
        return 0

    ap.error("nothing to do: use run/regen/--check/--list")
    return 2


if __name__ == "__main__":
    sys.exit(main())
