"""Weight-only quantization (int8 w8a16, group-wise int4 w4a16, dynamic
w8a8) for decode-bandwidth-bound serving.

Single-sequence decode reads every weight byte once per token, so tok/s is
capped by weights-bytes/HBM-bandwidth (scaling-book roofline). The reference
serves bf16 torch weights and has no quantization story
(/root/reference/models/qwen3/server/qwen3_server_module.py:212-217); halving
the bytes with int8 weights + per-output-channel float scales roughly doubles
the bs=1 decode ceiling on a v5e while keeping activations, KV cache, norms,
router, and embedding in bf16 (the quality-sensitive parts).

Scheme: symmetric per-output-channel. For a weight W [..., K, N] contracted
over K, scale[..., n] = max_k |W[..., k, n]| / 127 and q = round(W / scale).
Because the scale is per OUTPUT channel, `x @ W  ==  (x @ q) * scale` exactly
— so the dequant multiply rides AFTER the matmul on the [.., N] result and
the MXU sees the int8 tensor directly (no [K, N] bf16 rematerialization in
HBM, which would forfeit the bandwidth win).

`QuantWeight` is a pytree node: stacked-layer `lax.scan`, stage slicing
(models.qwen3.slice_layers), checkpointing, and tree.map-based sharding all
work unchanged on the (q, scale) leaves.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from inferd_tpu.utils.platform import is_tpu

Params = Any


@dataclasses.dataclass
class _QWeightBase:
    """Shared (q, scale) pytree/duck-typing contract for every quantized
    weight format: two array leaves, and `shape`/`ndim` mirroring the
    ORIGINAL weight so model code can stay format-agnostic."""

    q: jax.Array
    scale: jax.Array

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):  # duck-type the original weight's shape
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantWeight(_QWeightBase):
    """int8 weights + per-output-channel scales for one linear layer.

    q:     int8 [..., K, N]  (same leading/batch dims as the original)
    scale: float32 [..., N]  (contraction axis reduced away)
    """

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.scale[..., None, :]).astype(dtype)


def quantize(w: jax.Array) -> QuantWeight:
    """Symmetric per-output-channel int8 over the second-to-last axis
    (the contraction axis of every linear in models/)."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2)  # [..., N]
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(wf / scale[..., None, :]), -127, 127).astype(jnp.int8)
    return QuantWeight(q=q, scale=scale)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Int4Weight(_QWeightBase):
    """GROUP-WISE int4 weights (w4a16) for one linear layer: quarter the
    HBM bytes of bf16 (the bs=1 decode ceiling doubles again vs int8).

    q:     int8 [..., K/2, N] with TWO 4-bit two's-complement values
           packed per byte along the CONTRACTION axis (packed=True; odd-K
           tiny test configs fall back to one value per int8 byte,
           packed=False). The jnp.int4 dtype is deliberately avoided: on
           the round-5 hardware window, merely STAGING an S4[28,3072,1024]
           weight to the TPU crashed jit with a RecursionError, so the
           battery's int4 leg never produced an on-chip number and fell
           back to CPU (bench_artifacts/BENCH_tpu_r05.jsonl decode_int4,
           device:"cpu", note field) — int8 shift/mask unpacking is
           portable VPU code with no exotic-dtype staging path.
    scale: float32 [..., G, N] — G groups along the CONTRACTION axis
           (group size K/G, default 128; int4's 15 levels need per-group
           ranging to hold accuracy, per-output-channel like int8 would
           clip outliers badly).

    Because scales vary ALONG K, the dequant cannot ride after the whole
    dot the way the int8 per-output-channel scheme does. Two contraction
    schemes exist (see _int4_mode): "grouped" contracts per group on the
    narrow tensor and applies each group's scale to its partial sum with
    no full-rank float intermediate; "dequant" widens group-wise into one
    [K, N] operand and runs a single MXU dot (the widen fuses into the
    dot's operand stream, same contract as int8 "dequant" mode). Both are
    exact; which is faster is a hardware question, so the default is
    per-backend and measured, not assumed."""

    packed: bool = True

    def tree_flatten(self):
        return (self.q, self.scale), self.packed

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, packed=aux)

    @property
    def shape(self):  # duck-type the ORIGINAL [..., K, N] weight shape
        s = self.q.shape
        if not self.packed:
            return s
        return s[:-2] + (s[-2] * 2,) + s[-1:]

    def unpacked(self) -> jax.Array:
        """int8 [..., K, N] in [-7, 7]: arithmetic-shift nibble unpack
        (sign-extending), interleaved back to original K order."""
        if not self.packed:
            return self.q
        lo = jnp.left_shift(self.q, 4) >> 4  # low nibble, sign-extended
        hi = self.q >> 4  # high nibble, arithmetic shift sign-extends
        pair = jnp.stack([lo, hi], axis=-2)  # [..., K/2, 2, N]
        s = self.q.shape
        return pair.reshape(*s[:-2], s[-2] * 2, s[-1])

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        qi = self.unpacked()
        k, n = qi.shape[-2], qi.shape[-1]
        g = self.scale.shape[-2]
        qf = qi.astype(jnp.float32).reshape(*qi.shape[:-2], g, k // g, n)
        return (qf * self.scale[..., :, None, :]).reshape(qi.shape).astype(dtype)


def _group_size(k: int, group: int) -> int:
    """Largest divisor of K that is <= the requested group size (tiny test
    configs have K < 128; oddball K must still split exactly)."""
    g = min(group, k)
    while k % g:
        g -= 1
    return g


def quantize_int4(w: jax.Array, group: int = 128) -> Int4Weight:
    """Symmetric group-wise int4 over the contraction axis (-2), stored
    nibble-packed in int8 (two K-adjacent values per byte) when K is even."""
    k, n = w.shape[-2], w.shape[-1]
    gs = _group_size(k, group)
    wf = w.astype(jnp.float32).reshape(*w.shape[:-2], k // gs, gs, n)
    amax = jnp.max(jnp.abs(wf), axis=-2)  # [..., G, N]
    scale = jnp.where(amax == 0.0, 1.0, amax / 7.0)
    q = jnp.clip(jnp.round(wf / scale[..., :, None, :]), -7, 7)
    qi = q.reshape(w.shape).astype(jnp.int8)
    if k % 2:
        return Int4Weight(q=qi, scale=scale, packed=False)
    lo = qi[..., 0::2, :] & jnp.int8(0x0F)
    hi = jnp.left_shift(qi[..., 1::2, :], 4)
    return Int4Weight(q=(lo | hi).astype(jnp.int8), scale=scale, packed=True)


WeightLike = Union[jax.Array, QuantWeight, Int4Weight]

# How qdot/qeinsum contract against an int8 weight:
#   "dequant" — convert the int8 operand to the activation dtype inline and
#               run a bf16 MXU dot. Numerically the safest (w8a16); whether
#               the bandwidth win survives depends on XLA fusing the convert
#               into the dot's operand stream instead of rematerializing a
#               bf16 copy in HBM (measured on hardware via bench --quant).
#   "int8"    — dynamic symmetric per-row activation quantization, then a
#               native int8 x int8 -> int32 MXU dot (guaranteed: the int8
#               bytes are what crosses HBM, and v5e int8 matmul throughput
#               is 2x bf16). Output = xq @ wq * x_scale * w_scale.
#   "kernel"  — Pallas w8a16 matmul (ops/qmatmul.py): int8 blocks stream
#               through VMEM and dequantize in-register, making the
#               half-bandwidth read structural rather than dependent on
#               XLA fusing the convert (2D weights only; others fall back
#               to "dequant").
QDOT_MODE = "dequant"

# How Int4Weight contracts (see the class docstring for the two schemes):
#   "auto"    — "dequant" on TPU, "grouped" elsewhere. The grouped scheme
#               lowers to a G-batched stack of [1, K/G] x [K/G, N] matvecs
#               per matmul — a shape XLA:TPU tiles poorly onto the MXU —
#               while a single dot over the group-wise-widened operand is
#               the standard MXU mapping with the widen fused into its
#               operand stream. No on-chip int4 number exists yet (the
#               round-5 window's int4 leg crashed staging jnp.int4 weights
#               and fell back to CPU — BENCH_tpu_r05.jsonl decode_int4),
#               so the TPU default is the conservative scheme; the next
#               window's battery re-measures both via this flag.
#   "grouped" / "dequant" — force one scheme (tests, re-measurement).
INT4_MODE = "auto"

# Round-19 decode-GEMV kernel dispatch (ops/qmatmul.py): when the autotune
# registry's quant_decode entry carries kernel_*/xla_* rate pairs showing
# the Pallas kernels winning on this chip, qdot routes decode-shaped 2-D
# contractions through them — w8a16_matmul under QDOT_MODE="dequant" and
# w4a16_matvec for Int4Weight (mirroring whichever scheme _int4_mode
# picked). Cold registry -> the XLA paths, byte-identical. Tests force
# either side deterministically via this override.
FORCE_QUANT_KERNEL: Optional[bool] = None


def _quant_kernel_enabled() -> bool:
    if FORCE_QUANT_KERNEL is not None:
        return FORCE_QUANT_KERNEL
    from inferd_tpu.perf import autotune

    return autotune.quant_kernel_winner() == "kernel"


def _int4_mode() -> str:
    if INT4_MODE != "auto":
        return INT4_MODE
    # `auto` consults the autotune registry first (perf/autotune.py): a
    # hardware window that measured both schemes on this chip decides;
    # cold registry -> the frozen per-backend default, bit-for-bit.
    from inferd_tpu.perf import autotune

    measured = autotune.int4_winner()
    if measured is not None:
        return measured
    return "dequant" if is_tpu() else "grouped"


def _dynamic_quant_rows(x: jax.Array):
    """Per-row (last-axis) symmetric int8 activation quantization."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return xq.astype(jnp.int8), scale


def qdot(x: jax.Array, w: WeightLike) -> jax.Array:
    """x [..., K] @ w [K, N] where w may be quantized (see QDOT_MODE)."""
    if isinstance(w, Int4Weight):
        mode = _int4_mode()
        if w.ndim == 2 and _quant_kernel_enabled():
            from inferd_tpu.ops.qmatmul import MAX_KERNEL_ROWS, w4a16_matvec

            lead = x.shape[:-1]
            rows = 1
            for d in lead:
                rows *= d
            if rows <= MAX_KERNEL_ROWS:  # decode shapes; prefill falls through
                y2 = w4a16_matvec(
                    x.reshape(-1, x.shape[-1]), w, scheme=mode,
                    interpret=not is_tpu(),
                )
                return y2.reshape(lead + (w.shape[-1],))
        if w.ndim != 2 or mode == "dequant":
            return x @ w.dequantize(x.dtype)
        # grouped contraction: y = sum_g (x_g @ q_g) * s_g — the scales
        # vary along K, so each group's scale applies to its own partial
        # sum (exact; see Int4Weight)
        k, n = w.shape
        g = w.scale.shape[-2]
        xg = x.reshape(*x.shape[:-1], g, k // g)
        qg = w.unpacked().reshape(g, k // g, n).astype(x.dtype)
        y = jnp.einsum("...gk,gkn->...gn", xg, qg)
        return (
            (y.astype(jnp.float32) * w.scale).sum(axis=-2).astype(x.dtype)
        )
    if not isinstance(w, QuantWeight):
        return x @ w
    if w.q.ndim == 2 and (
        QDOT_MODE == "kernel"
        or (QDOT_MODE == "dequant" and _quant_kernel_enabled())
    ):
        from inferd_tpu.ops.qmatmul import MAX_KERNEL_ROWS, w8a16_matmul

        lead = x.shape[:-1]
        rows = 1
        for d in lead:
            rows *= d
        if rows <= MAX_KERNEL_ROWS:  # decode shapes; prefill falls through
            y2 = w8a16_matmul(
                x.reshape(-1, x.shape[-1]), w.q, w.scale,
                interpret=not is_tpu(),
            )
            return y2.reshape(lead + (w.q.shape[-1],))
    if QDOT_MODE == "int8":
        xq, xs = _dynamic_quant_rows(x)
        y = jax.lax.dot_general(
            xq, w.q, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
        return (y * xs * w.scale).astype(x.dtype)
    y = x @ w.q.astype(x.dtype)
    return (y.astype(jnp.float32) * w.scale).astype(x.dtype)


def _int4_grouped_einsum(spec: str, x: jax.Array, w: "Int4Weight"):
    """Grouped contraction for an Int4Weight under an arbitrary
    single-contraction einsum: split the contraction axis into (G, K/G) on
    BOTH operands, contract per group on the NARROW tensor, then apply
    each group's scale to its partial sum and reduce over groups in one
    final einsum — the exact int4 sibling of the dense qdot path, for the
    MoE expert einsums ("th,ehi->tei", "tei,eih->teh"). The int4 bytes are
    what crosses HBM; no full-rank float intermediate is materialized
    (VERDICT r04 weak #3 / ADVICE quant.py:214). Returns None when the
    spec shape doesn't fit (caller falls back to inline dequant)."""
    try:
        ins, out = spec.split("->")
        xs_, ws_ = ins.split(",")
    except ValueError:
        return None
    shared = [ch for ch in ws_ if ch in xs_ and ch not in out]
    if len(shared) != 1:
        return None
    c = shared[0]
    # quantize_int4 groups along the weight's -2 axis; x contracts on it
    if ws_.index(c) != len(ws_) - 2 or xs_.index(c) != len(xs_) - 1:
        return None
    # every OTHER weight axis must survive into the output: an axis summed
    # out before the scale multiply would apply sum-of-scales to a
    # sum-of-partials — silently wrong; the dequant fallback handles it
    if any(ch not in out for ch in ws_ if ch != c):
        return None
    g_letter = next(ch for ch in "gzyxwvu" if ch not in spec)
    qi = w.unpacked()
    k = qi.shape[-2]
    G = w.scale.shape[-2]
    gs = k // G
    xg = x.reshape(x.shape[:-1] + (G, gs))
    qg = qi.reshape(qi.shape[:-2] + (G, gs, qi.shape[-1])).astype(x.dtype)
    xs2 = xs_.replace(c, g_letter + c)
    ws2 = ws_.replace(c, g_letter + c)
    y = jnp.einsum(f"{xs2},{ws2}->{g_letter}{out}", xg, qg)
    # scale [..., G, N] carries the weight's non-contraction letters with
    # the contraction groups in place of c: scale-and-sum-over-groups in
    # one einsum (pure broadcast + reduction, no hidden contraction)
    return jnp.einsum(
        f"{g_letter}{out},{ws_.replace(c, g_letter)}->{out}",
        y.astype(jnp.float32), w.scale,
    ).astype(x.dtype)


def qeinsum(spec: str, x: jax.Array, w: WeightLike) -> jax.Array:
    """einsum over a possibly-quantized weight whose scale is per-output
    (valid iff every non-contracted weight axis survives in the output,
    which holds for the MoE expert einsums in models/qwen3.py: the scale
    axes trail the einsum output, e.g. [t,e,i] * scale[e,i])."""
    if isinstance(w, Int4Weight):
        if _int4_mode() == "grouped":
            y = _int4_grouped_einsum(spec, x, w)
            if y is not None:
                return y
        # dequant mode or unrecognized spec shape: one einsum over the
        # group-wise-widened operand (the widen fuses into the einsum's
        # operand stream; on TPU this is the MXU-mapped path)
        return jnp.einsum(spec, x, w.dequantize(x.dtype))
    if not isinstance(w, QuantWeight):
        return jnp.einsum(spec, x, w)
    if QDOT_MODE == "int8":
        xq, xs = _dynamic_quant_rows(x)
        y = jnp.einsum(spec, xq, w.q, preferred_element_type=jnp.int32)
        # x's batch axes lead the output in the model's einsums; pad the
        # per-row scale with trailing singleton dims to broadcast over the
        # weight-derived output axes
        xs_lead = xs[..., 0]
        xs_b = xs_lead.reshape(xs_lead.shape + (1,) * (y.ndim - xs_lead.ndim))
        return (y.astype(jnp.float32) * xs_b * w.scale).astype(x.dtype)
    y = jnp.einsum(spec, x, w.q.astype(x.dtype))
    return (y.astype(jnp.float32) * w.scale).astype(x.dtype)


# Leaves to quantize in a layers pytree (stacked [L, ...] — the per-layer
# contraction axis is still axis -2) and in the top-level params dict.
# Deliberately NOT listed: "router" — routing precision is quality-critical
# and the matrix is tiny.
_LAYER_LINEARS = (
    "q_proj", "k_proj", "v_proj", "o_proj", "gate_proj", "up_proj", "down_proj",
)


def quantize_params(
    params: Params, tie_word_embeddings: bool = False, needs_head: bool = True,
    quantizer=quantize,
) -> Params:
    """Quantize every linear projection of a full-model / stage param tree.

    Kept in bf16: embedding table (the gather source), norms, biases,
    router. Untied lm_head [H, V] is quantized in place. For tied models
    the unembed matmul — the single largest weight read per decode step
    (H x V, 311 MB bf16 for Qwen3-0.6B) — gets a quantized SHADOW copy
    under "lm_head_q" (int8 of embed.T, +V/2 extra bytes vs the halved
    read) which models.qwen3.unembed prefers when present; the bf16 table
    still serves the embedding gather. Pass needs_head=False for pipeline
    stages that hold embed only for the token gather (non-last stages) so
    they don't allocate a dead shadow head.
    """
    out = dict(params)
    qtypes = (QuantWeight, Int4Weight)
    if "layers" in out:
        layers = dict(out["layers"])
        for name in _LAYER_LINEARS:
            if name in layers and not isinstance(layers[name], qtypes):
                layers[name] = quantizer(layers[name])
        out["layers"] = layers
    if "lm_head" in out and not isinstance(out["lm_head"], qtypes):
        out["lm_head"] = quantizer(out["lm_head"])
    elif (
        needs_head
        and tie_word_embeddings
        and "embed" in out
        and "lm_head_q" not in out
    ):
        out["lm_head_q"] = quantizer(out["embed"].T)
    return out


# quant flags already warned-about this process (one line per flag, not
# one per model load)
_quant_warned: set = set()


def _warn_if_slower_than_bf16(flag: str) -> None:
    """Loud (stderr, once per flag per process) when the autotune registry
    holds a MEASURED decode rate for this quant flag that is below the
    same sweep's bf16 baseline on this chip — the r05 inversion ("int8
    0.69x bf16") must never be picked silently again. The flag is still
    honored (it is an explicit operator choice and the inversion is
    window-weather-sensitive); the committed rates in
    bench_artifacts/autotune.json are the record of why it stands.

    RETIRED when the same entry's round-19 kernel grading shows the Pallas
    decode-GEMV kernel for this flag's scheme winning its XLA sibling AND
    beating the bf16 baseline: dispatch then routes decode through the
    kernel (_quant_kernel_enabled), so the flag-sweep inversion no longer
    describes the serving path. Cold hosts (no kernel rates) keep the
    warning."""
    import sys

    if flag in _quant_warned:
        return
    try:
        from inferd_tpu.perf import autotune

        rates = autotune.quant_rates()
    except Exception:
        return  # cold/absent registry: nothing measured, nothing to say
    if not rates:
        return
    bf16, q = rates.get("bf16"), rates.get(flag)
    scheme = {"int8": "int8", "int8-kernel": "int8", "int4": "int4"}.get(flag)
    if scheme is not None and bf16:
        kern = rates.get(f"kernel_{scheme}")
        if (
            kern
            and kern >= bf16
            and autotune.quant_kernel_winner() == "kernel"
        ):
            return  # the fused kernel carries this flag's decode path now
    if bf16 and q and q < bf16:
        _quant_warned.add(flag)
        print(
            f"quant: measured decode rate for {flag!r} ({q:.1f}) is BELOW "
            f"the bf16 baseline ({bf16:.1f}) on this chip "
            "(bench_artifacts/autotune.json, sweep_attn --quant) — "
            "serving it anyway as requested",
            file=sys.stderr,
        )


def apply_quant_mode(
    flag: str,
    params: Params,
    tie_word_embeddings: bool = False,
    needs_head: bool = True,
) -> Params:
    """Single entry point for the CLI-facing quant flags ("none" | "int8" |
    "w8a8" | "int8-kernel" | "int4"): sets QDOT_MODE and quantizes the
    tree. Used by
    the node runtime, bench, and the generate CLI so the flag->mode mapping
    cannot diverge between surfaces. When the autotune registry carries a
    measured bf16-vs-quant decode rate for this chip showing the flag
    LOSING to bf16, a one-line stderr warning says so (never silent)."""
    global QDOT_MODE
    if flag == "none":
        return params
    _warn_if_slower_than_bf16(flag)
    if flag == "int4":
        # group-wise w4a16: QDOT_MODE is irrelevant (Int4Weight carries
        # its own contraction scheme), but reset it so a process that
        # switched modes earlier doesn't leak "int8"/"kernel" behavior
        # onto any residual QuantWeight leaves
        QDOT_MODE = "dequant"
        return quantize_params(
            params, tie_word_embeddings=tie_word_embeddings,
            needs_head=needs_head, quantizer=quantize_int4,
        )
    QDOT_MODE = {"w8a8": "int8", "int8-kernel": "kernel"}.get(flag, "dequant")
    return quantize_params(
        params, tie_word_embeddings=tie_word_embeddings, needs_head=needs_head
    )


def quantized_bytes(params: Params) -> int:
    """Total parameter bytes AS STORED (int8/int4 + scales + residual
    bf16). Even-K Int4Weight nibble-packs two values per int8 byte, so
    size*itemsize counts it at half; the odd-K fallback genuinely stores
    one value per byte (tiny test configs only) and is counted as such."""
    return sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(params)
    )
