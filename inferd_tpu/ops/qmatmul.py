"""Pallas TPU w8a16 matmul: int8 weight tiles stream through VMEM and
dequantize in-register.

The quantization module's dequant-in-dot path (ops/quant.py QDOT_MODE=
"dequant") relies on XLA fusing `convert(int8->bf16) * scale` into the
dot's operand stream; if XLA materializes the converted weights instead,
the HBM read doubles back to bf16 size and the w8a16 bandwidth win
evaporates. This kernel makes the win structural: pallas_call's pipeline
fetches int8 blocks (half the bytes of bf16 — the only weight bytes that
cross HBM), converts them in VMEM, and feeds the MXU, with the per-output-
channel scale applied to the f32 accumulator.

Decode shapes are the target: x [M, K] with tiny M (1..64 rows = batch
lanes), W [K, N] with K = hidden (fits VMEM whole), N up to vocab-size
(gridded). The reference has no analogue (bf16 torch matmuls,
qwen3_server_module.py); this is the TPU-native hot-op layer the north
star asks for.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _w8a16_kernel(x_ref, q_ref, s_ref, o_ref, *, out_dtype):
    # x_ref [M_pad, K] activation (bf16/f32), whole — M is tiny at decode
    # q_ref [K, bn] int8 weight block (the streamed operand)
    # s_ref [1, bn] f32 per-output-channel scales
    # o_ref [M_pad, bn]
    x = x_ref[...]
    w = q_ref[...].astype(x.dtype)  # int8 -> activation dtype, in VMEM
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    o_ref[...] = (acc * s_ref[0]).astype(out_dtype)


# The kernel targets DECODE shapes: a handful of activation rows against a
# huge weight. Past this many rows (long prefill) the whole-x VMEM block
# would not fit and the dequant-in-dot path wins anyway (compute-bound).
MAX_KERNEL_ROWS = 64


def w8a16_matmul(
    x: jax.Array,  # [M, K] bf16/f32, M <= MAX_KERNEL_ROWS
    q: jax.Array,  # [K, N] int8
    scale: jax.Array,  # [N] f32
    *,
    block_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """x @ dequantize(q, scale) with int8 as the only weight bytes read.

    Returns [M, N] in x.dtype. K must fit VMEM as an [K, block_n] int8
    block (K=1024..8192 with block_n=512 is 0.5..4 MB — fine). The weight
    and scale are NOT padded host-side (a jnp.pad of a vocab-size lm_head
    would copy ~150 MB through HBM per step); the N tail rides Pallas'
    boundary-block semantics — out-of-range lanes read garbage and their
    output columns are sliced off."""
    m, k = x.shape
    kq, n = q.shape
    assert k == kq, (x.shape, q.shape)
    assert m <= MAX_KERNEL_ROWS, (m, "use the dequant path for prefill")
    m_pad = _round_up(max(m, 8), 8)
    bn = min(block_n, _round_up(n, 128))

    xp = jnp.pad(x, ((0, m_pad - m), (0, 0)))  # tiny (decode rows)
    sp = scale.astype(jnp.float32)[None, :]  # [1, N]

    out = pl.pallas_call(
        functools.partial(_w8a16_kernel, out_dtype=x.dtype),
        grid=(pl.cdiv(n, bn),),
        in_specs=[
            pl.BlockSpec((m_pad, k), lambda j: (0, 0)),
            pl.BlockSpec((k, bn), lambda j: (0, j)),
            pl.BlockSpec((1, bn), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((m_pad, bn), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, n), x.dtype),
        interpret=interpret,
    )(xp, q, sp)
    return out[:m, :n]


def _w4a16_kernel(
    x_ref,  # [M_pad, K] activation, whole — M is tiny at decode
    q_ref,  # [Kp, bn] int8: nibble-PACKED int4 weight block (Kp = K/2),
    #         or plain [-7, 7] bytes when packed=False (odd-K tiny configs)
    s_ref,  # [G, bn] f32 group scales (groups along K)
    o_ref,  # [M_pad, bn]
    *,
    out_dtype,
    groups: int,
    packed: bool,
    scheme: str,  # "dequant" | "grouped" — mirrors ops/quant._int4_mode
):
    """Dequant-fused int4 decode GEMV: the packed nibbles are the ONLY
    weight bytes that cross HBM (quarter of bf16); unpack (arithmetic-
    shift sign extension, the exact Int4Weight.unpacked recipe) and the
    group-scale application both happen in VMEM. Both Int4Weight
    contraction schemes are implemented so the kernel's sibling is
    whatever _int4_mode picked — "dequant" widens group-wise and runs ONE
    dot; "grouped" contracts per group on the narrow tensor and applies
    each group's scale to its own partial sum (static unroll: G is
    K/group_size, a handful)."""
    x = x_ref[...]
    q = q_ref[...]
    if packed:
        lo = jnp.left_shift(q, 4) >> 4  # low nibble, sign-extended
        hi = q >> 4  # arithmetic shift sign-extends
        w = jnp.stack([lo, hi], axis=-2).reshape(2 * q.shape[0], q.shape[1])
    else:
        w = q
    k, bn = w.shape
    gs = k // groups
    if scheme == "dequant":
        wf = w.astype(jnp.float32).reshape(groups, gs, bn) * s_ref[...][:, None, :]
        acc = jax.lax.dot_general(
            x, wf.reshape(k, bn).astype(x.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    else:
        acc = jnp.zeros((x.shape[0], bn), jnp.float32)
        for g in range(groups):
            yg = jax.lax.dot_general(
                x[:, g * gs:(g + 1) * gs],
                w[g * gs:(g + 1) * gs].astype(x.dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc = acc + yg * s_ref[g]
    o_ref[...] = acc.astype(out_dtype)


def w4a16_matvec(
    x: jax.Array,  # [M, K] bf16/f32, M <= MAX_KERNEL_ROWS
    w,  # ops.quant.Int4Weight with 2-D q (one linear's weight)
    *,
    scheme: str = "dequant",  # which XLA sibling to mirror (_int4_mode)
    block_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """x @ w for a group-wise Int4Weight at decode GEMV shapes, nibble
    bytes streamed through VMEM with dequant fused into the contraction.

    Returns [M, N] in x.dtype. Same boundary-block contract as
    w8a16_matmul: weight/scales are NOT padded host-side; the N tail's
    out-of-range lanes read garbage that the final slice drops."""
    m, k = x.shape
    kk, n = w.shape  # ORIGINAL [K, N] (Int4Weight duck-types it)
    assert k == kk, (x.shape, w.shape)
    assert m <= MAX_KERNEL_ROWS, (m, "use the dequant path for prefill")
    groups = w.scale.shape[-2]
    m_pad = _round_up(max(m, 8), 8)
    bn = min(block_n, _round_up(n, 128))
    kp = w.q.shape[-2]  # K/2 packed rows (or K when packed=False)

    xp = jnp.pad(x, ((0, m_pad - m), (0, 0)))

    out = pl.pallas_call(
        functools.partial(
            _w4a16_kernel, out_dtype=x.dtype, groups=groups,
            packed=w.packed, scheme=scheme,
        ),
        grid=(pl.cdiv(n, bn),),
        in_specs=[
            pl.BlockSpec((m_pad, k), lambda j: (0, 0)),
            pl.BlockSpec((kp, bn), lambda j: (0, j)),
            pl.BlockSpec((groups, bn), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((m_pad, bn), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, n), x.dtype),
        interpret=interpret,
    )(xp, w.q, w.scale.astype(jnp.float32))
    return out[:m, :n]
