"""Pallas TPU flash attention — the framework's hot-op kernel layer.

The reference computes attention eagerly, materializing the full [S, T] score
matrix per head (/root/reference/models/qwen3/server/qwen3_server_module.py:67-89)
and rebuilding a dense causal mask every call (partitioned_models.py:28-35).
On TPU that is HBM-bandwidth-bound and O(S*T) memory. This module replaces it
with a flash-style kernel designed for the hardware:

  * online-softmax accumulation — nothing bigger than [block_q, block_k] is
    ever materialized; running max/denominator keep the result exact;
  * both matmuls (q@k^T and p@v) hit the MXU in the input dtype with float32
    accumulation (`preferred_element_type`);
  * two kernels behind one call: a RESIDENT kernel (whole K/V per
    (batch, kv-head) in VMEM, causal early exit — fastest under the VMEM
    budget) and a STREAMING kernel (kv blocks on an inner grid axis with
    online-softmax state in VMEM scratch — O(block) VMEM, no buffer-length
    cap, the long-context path); both express GQA sharing in the index map
    (`h // group` selects the kv head, so K/V is never duplicated);
  * causality + cache-validity masking is positional arithmetic inside the
    kernel (no mask tensor on the wire or in HBM), and the kv-block loop
    early-exits past the causal frontier (`hi` bound), so decode steps with a
    short cache do O(valid) work, not O(buffer).

Layout contract (matches the KV cache + stage executor): kv slot `j` holds
absolute position `kv_start + j`; queries are contiguous from `q_start`
(per batch). The general scattered-position case stays on the XLA path
(models/qwen3.gqa_attention).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from inferd_tpu.utils.platform import is_tpu

NEG_INF = -1e30  # python float: jax arrays captured by a pallas kernel are rejected

# Auto-dispatch cap: per-head K + V VMEM footprint (bytes). ~16 MB VMEM/core,
# but Pallas double-buffers pipelined inputs (~2x the K/V block) and the
# kernel also needs q/out blocks plus f32 accumulators — so admit only KV
# sizes well under half of VMEM, and fall back to XLA past it.
_VMEM_KV_BUDGET = 4 * 1024 * 1024


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def apply_softcap(x: jax.Array, cap: float) -> jax.Array:
    """Gemma logit softcapping: cap * tanh(x / cap); cap == 0 is identity.
    Pure jnp — shared by the XLA attention path, both Pallas kernels, and
    the unembed heads so the formula can't drift between paths."""
    if not cap:
        return x
    return cap * jnp.tanh(x / cap)


def apply_window_mask(
    mask: jax.Array,  # [B, S, T] bool: already causal/valid-masked
    kpos: jax.Array,  # [B, T] absolute position per kv slot
    q_positions: jax.Array,  # [B, S]
    window,  # traced int32 scalar or None; <= 0 = global
) -> jax.Array:
    """AND the sliding-window predicate — keep kv iff its position is in
    (qpos - window, qpos] — into an attention mask. One definition shared
    by the XLA path (models/qwen3.gqa_attention) and ring attention
    (parallel/ring.py) so the boundary convention can't drift between the
    single-device and sequence-parallel numerics."""
    if window is None:
        return mask
    win = jnp.asarray(window, jnp.int32)
    in_win = kpos[:, None, :] > (q_positions[:, :, None] - win)
    return mask & ((win <= 0) | in_win)


def gather_block_kv(
    k_pool: jax.Array,  # [NB, bs, Nkv, D] — one layer's paged block pool
    v_pool: jax.Array,
    block_table: jax.Array,  # [B, MB] int32 lane -> block chain
):
    """Dense position-contiguous K/V views gathered through a block table
    (the paged-KV read path, core.cache.PagedKVCache layout).

    Chain slot j of lane b covers absolute positions [j*bs, (j+1)*bs), so
    the gathered [B, MB*bs, Nkv, D] view has slot index == absolute
    position — EXACTLY the dense cache layout, which is what makes the
    block-table attention path token-exact vs the dense path: the same
    causal/validity mask applies unchanged, and unallocated table entries
    (0 -> the scratch block) are only ever read at masked slots. The
    gather preserves the storage dtype, so compressed-KV layouts
    (cfg.kv_dtype) keep their dequant-fused upcast downstream."""
    b, mb = block_table.shape
    bs = k_pool.shape[1]
    kd = k_pool[block_table]  # [B, MB, bs, Nkv, D]
    vd = v_pool[block_table]
    return (
        kd.reshape(b, mb * bs, *k_pool.shape[2:]),
        vd.reshape(b, mb * bs, *v_pool.shape[2:]),
    )


def _fold_sink(m, l, acc, sink_ref, hh, qi, rows, block_q, rows_per_head):
    """Fold per-head sink logits into the online-softmax state (shared by
    the resident and streaming kernels so the formula can't drift): packed
    row r belongs to head group (qi*bq + r) // S_pad, its sink is read from
    SMEM by a STATIC unroll over the (small) group, and the state is
    rescaled by the new max with exp(sink) joining the denominator — exact.
    NEG_INF sinks (models without the feature) are a no-op."""
    row_group = (qi * block_q + rows) // rows_per_head  # [block_q, 1]
    sink = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    for gg in range(sink_ref.shape[1]):
        sink = jnp.where(row_group == gg, sink_ref[hh, gg], sink)
    m_f = jnp.maximum(m, sink)
    alpha_f = jnp.exp(m - m_f)
    l = l * alpha_f + jnp.where(sink > NEG_INF / 2, jnp.exp(sink - m_f), 0.0)
    return l, acc * alpha_f


def _kv_fits_vmem(kv_buf_len: int, head_dim: int, dtype) -> bool:
    itemsize = jnp.dtype(dtype).itemsize
    return 2 * _round_up(kv_buf_len, 128) * head_dim * itemsize <= _VMEM_KV_BUDGET


def _flash_kernel(
    meta_ref,  # SMEM [B, 4] int32 (whole array — batch-blocked SMEM rows
    #           fail Mosaic's divisible-by-8 block rule): (q_start, kv_start,
    #           kv_len, window) per batch row; window <= 0 = global
    sink_ref,  # SMEM [Nkv, G] f32 (whole array, like meta) — per-head sink
    #           logits (NEG_INF when the model has no sinks)
    q_ref,  # VMEM [1, 1, block_q, D] — a tile of the GQA-PACKED query axis
    k_ref,  # VMEM [1, 1, T_pad, D]
    v_ref,  # VMEM [1, 1, T_pad, D]
    o_ref,  # VMEM [1, 1, block_q, D]
    *,
    block_q: int,
    block_k: int,
    num_kv_blocks: int,
    scale: float,
    rows_per_head: int,  # S_pad: the packed axis is G heads x S_pad rows
    softcap: float = 0.0,  # Gemma attn logit softcapping; 0 = off
):
    bb = pl.program_id(0)
    qi = pl.program_id(2)
    q_start = meta_ref[bb, 0]
    kv_start = meta_ref[bb, 1]
    kv_len = meta_ref[bb, 2]
    win = meta_ref[bb, 3]

    q = q_ref[0, 0]  # [block_q, D], input dtype
    d = q.shape[-1]
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    # packed layout: grid axis 1 is the KV head; the query axis concatenates
    # the G heads of its group (G x S_pad rows). A row's sequence position
    # is its packed index modulo S_pad — rows of different heads coexist in
    # a tile (softmax/mask are per-row, positions repeat per head)
    q_pos = q_start + (qi * block_q + rows) % rows_per_head  # [block_q, 1]

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    # causal frontier: the highest position in this tile is
    # (qi*bq) % S_pad + min(bq, S_pad) - 1 (bq divides S_pad or is a
    # multiple of it — guaranteed by flash_gqa's tile sizing)
    tile_hi = (qi * block_q) % rows_per_head + min(block_q, rows_per_head)
    last_slot = jnp.minimum(kv_len, q_start + tile_hi - kv_start)
    hi = jnp.clip(pl.cdiv(last_slot, block_k), 0, num_kv_blocks)
    # sliding-window floor: the tile's LOWEST query position bounds the
    # first kv block any row can see — local layers do O(window) compute
    # (K/V is already VMEM-resident here, so skipped blocks skip reads too)
    tile_lo_pos = q_start + (qi * block_q) % rows_per_head
    lo_slot = jnp.where(win > 0, tile_lo_pos - win + 1 - kv_start, 0)
    lo = jnp.clip(lo_slot // block_k, 0, num_kv_blocks)

    def body(j, carry):
        m, l, acc = carry
        # compressed KV storage (cfg.kv_dtype): the narrow dtype is what the
        # pipeline fetched into VMEM; upcast in-register before the MXU dot
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(q.dtype)
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(q.dtype)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]
        s = apply_softcap(s, softcap)
        slot = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        kpos = kv_start + slot
        mask = (slot < kv_len) & (kpos <= q_pos)
        mask &= (win <= 0) | (kpos > q_pos - win)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(lo, hi, body, (m0, l0, acc0))
    # GPT-OSS attention sinks join the softmax denominator (_fold_sink)
    hh = pl.program_id(1)
    l, acc = _fold_sink(m, l, acc, sink_ref, hh, qi, rows, block_q, rows_per_head)
    # rows with no valid kv (bucket padding) have l == 0; emit zeros, not NaN
    out = acc / jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _flash_kernel_stream(
    meta_ref,  # SMEM [B, 4] int32 (whole array, see _flash_kernel):
    #           (q_start, kv_start, kv_len, window) per batch row
    sink_ref,  # SMEM [Nkv, G] f32 (whole array) — sinks (NEG_INF = none)
    q_ref,  # VMEM [1, 1, block_q, D] — a tile of the GQA-PACKED query axis
    k_ref,  # VMEM [1, 1, block_k, D] — ONE kv block (streamed from HBM)
    v_ref,  # VMEM [1, 1, block_k, D]
    o_ref,  # VMEM [1, 1, block_q, D]
    m_scr,  # VMEM scratch [block_q, 1] f32 — running max, lives across kv steps
    l_scr,  # VMEM scratch [block_q, 1] f32 — running denominator
    acc_scr,  # VMEM scratch [block_q, D] f32 — running numerator
    *,
    block_q: int,
    block_k: int,
    num_kv_blocks: int,
    scale: float,
    rows_per_head: int,  # S_pad: the packed axis is G heads x S_pad rows
    softcap: float = 0.0,  # Gemma attn logit softcapping; 0 = off
):
    """Streaming variant: the kv-block index is the INNERMOST grid axis, so
    K/V stream through VMEM one [block_k, D] tile at a time while the
    online-softmax state persists in scratch — the whole buffer never has to
    fit in VMEM, which lifts the ~8K-token admission cap of the resident
    kernel (VERDICT r1 A6). TPU grids iterate sequentially (row-major, last
    axis fastest), which is what makes the scratch carry correct."""
    bb = pl.program_id(0)
    hh = pl.program_id(1)
    qi = pl.program_id(2)
    j = pl.program_id(3)
    q_start = meta_ref[bb, 0]
    kv_start = meta_ref[bb, 1]
    kv_len = meta_ref[bb, 2]
    win = meta_ref[bb, 3]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    q_pos = q_start + (qi * block_q + rows) % rows_per_head
    # causal frontier (same arithmetic as the resident kernel): blocks at or
    # past it contribute nothing — skip their compute (their HBM fetch still
    # happens; the win of the resident kernel's early exit trades against
    # unbounded buffer size here)
    tile_hi = (qi * block_q) % rows_per_head + min(block_q, rows_per_head)
    last_slot = jnp.minimum(kv_len, q_start + tile_hi - kv_start)
    hi = jnp.clip(pl.cdiv(last_slot, block_k), 0, num_kv_blocks)
    # sliding-window floor (see _flash_kernel): local layers skip compute
    # for blocks wholly below every row's window
    tile_lo_pos = q_start + (qi * block_q) % rows_per_head
    lo_slot = jnp.where(win > 0, tile_lo_pos - win + 1 - kv_start, 0)
    lo = jnp.clip(lo_slot // block_k, 0, num_kv_blocks)

    @pl.when((j >= lo) & (j < hi))
    def _compute():
        q = q_ref[0, 0]
        kb = k_ref[0, 0].astype(q.dtype)  # compressed KV: upcast in VMEM
        vb = v_ref[0, 0].astype(q.dtype)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        s = apply_softcap(s, softcap)
        slot = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        kpos = kv_start + slot
        mask = (slot < kv_len) & (kpos <= q_pos)
        mask &= (win <= 0) | (kpos > q_pos - win)
        s = jnp.where(mask, s, NEG_INF)
        m, l, acc = m_scr[...], l_scr[...], acc_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_scr[...] = m_new
        l_scr[...] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc * alpha + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_kv_blocks - 1)
    def _finalize():
        # sink fold-in at finalize (shared _fold_sink)
        l, acc = _fold_sink(
            m_scr[...], l_scr[...], acc_scr[...],
            sink_ref, hh, qi, rows, block_q, rows_per_head,
        )
        out = acc / jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def flash_gqa(
    q: jax.Array,  # [B, S, Nq, D]
    k: jax.Array,  # [B, T, Nkv, D] — kv buffer (slot j = position kv_start + j)
    v: jax.Array,  # [B, T, Nkv, D]
    q_start: Union[jax.Array, int],  # scalar or [B]: absolute pos of q[:, 0]
    kv_len: Union[jax.Array, int],  # scalar or [B]: valid kv slots
    kv_start: Union[jax.Array, int] = 0,  # scalar or [B]: abs pos of slot 0
    *,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    stream: Optional[bool] = None,
    scale: Optional[float] = None,  # score scale; default head_dim**-0.5
    softcap: float = 0.0,  # Gemma attn logit softcapping (static)
    window: Optional[Union[jax.Array, int]] = None,  # sliding window; traced
    #   scalar OK (rides the SMEM meta row); None/<=0 = global
    sinks: Optional[jax.Array] = None,  # [Nq] per-q-head sink logits
    #   (GPT-OSS): folded into the softmax denominator at finalize
) -> jax.Array:
    """Flash GQA attention over a (possibly oversized) KV buffer.

    Exact match for models/qwen3.gqa_attention when kv slots hold contiguous
    positions. Returns [B, S, Nq*D] in q.dtype.

    Gemma-2 features are first-class: `softcap` caps scores pre-mask,
    `scale` overrides the head_dim**-0.5 default (query_pre_attn_scalar),
    and `window` restricts attention to (qpos - window, qpos] — a TRACED
    scalar, so the per-layer window array of a stacked-layer scan works,
    and both kernels bound their kv-block loop to the window. This is an
    O(window) COMPUTE bound, and on the resident kernel (K/V VMEM-resident)
    an O(window) read bound too; the streaming kernel's grid still DMAs
    every K/V tile from HBM, so its HBM traffic stays O(T) — the O(window)
    HBM-read win for sliding layers comes from the `_windowed_slice` fast
    path in models/qwen3.py, which slices the buffer before any backend.

    Two kernels behind one surface, picked by `stream` (None = auto):
      * resident — whole K/V per (batch, kv-head) in VMEM, early exit at the
        causal frontier; fastest for buffers under the VMEM budget;
      * streaming — kv blocks ride an inner grid axis through VMEM with the
        online-softmax state in scratch; admits arbitrarily long buffers
        (O(block) VMEM), so long-context decode never falls back to the
        score-materializing XLA path (the reference's weakness this module
        exists to kill, qwen3_server_module.py:67-89).
    """
    b, s, nq, d = q.shape
    t, nkv = k.shape[1], k.shape[2]
    g = nq // nkv

    # GQA PACKING: the query grid axis is the KV head; the g query heads of
    # a group concatenate along the row axis ([G * S_pad, D] per kv head).
    # One K/V fetch serves the whole group (g-fold less K/V traffic than a
    # per-q-head grid), and small-S tiles (decode: S == 1) pack multiple
    # heads into one MXU tile. Tile sizing keeps bq either a divisor or a
    # multiple of S_pad so the kernels' modulo position arithmetic holds.
    s_pad = _round_up(s, 16)
    if s_pad >= block_q:
        s_pad = _round_up(s, block_q)
        bq = block_q
    else:
        hpt = max(1, block_q // s_pad)  # head rows per tile, must divide g
        while g % hpt:
            hpt -= 1
        bq = hpt * s_pad
    packed = g * s_pad
    bk = min(block_k, _round_up(t, 128))
    t_pad = _round_up(t, bk)
    if stream is None:
        # admission by the STORED dtype: compressed KV (cfg.kv_dtype)
        # halves the footprint, so twice the context stays resident
        stream = not _kv_fits_vmem(t, d, k.dtype)

    # [B, Nq, S, D] -> [B, Nkv, G*S_pad, D] (heads kv*g..kv*g+g-1 = group)
    qt = jnp.pad(q.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
    qt = qt.reshape(b, nkv, packed, d)
    kt = jnp.pad(k.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))
    vt = jnp.pad(v.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))

    def as_b(x):
        arr = jnp.asarray(x, jnp.int32)
        return jnp.broadcast_to(arr, (b,)) if arr.ndim == 0 else arr

    win = jnp.int32(0) if window is None else window
    meta = jnp.stack(
        [as_b(q_start), as_b(kv_start), as_b(kv_len), as_b(win)], axis=1
    )  # [B, 4]
    eff_scale = float(scale) if scale is not None else 1.0 / math.sqrt(d)
    if sinks is None:
        sink_arr = jnp.full((nkv, g), NEG_INF, jnp.float32)
    else:
        sink_arr = sinks.astype(jnp.float32).reshape(nkv, g)

    if stream:
        kernel = functools.partial(
            _flash_kernel_stream,
            block_q=bq,
            block_k=bk,
            num_kv_blocks=t_pad // bk,
            scale=eff_scale,
            rows_per_head=s_pad,
            softcap=softcap,
        )
        out = pl.pallas_call(
            kernel,
            grid=(b, nkv, packed // bq, t_pad // bk),
            in_specs=[
                pl.BlockSpec((b, 4), lambda bb, h, i, j: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec((nkv, g), lambda bb, h, i, j: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, bq, d), lambda bb, h, i, j: (bb, h, i, 0)),
                pl.BlockSpec((1, 1, bk, d), lambda bb, h, i, j: (bb, h, j, 0)),
                pl.BlockSpec((1, 1, bk, d), lambda bb, h, i, j: (bb, h, j, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, bq, d), lambda bb, h, i, j: (bb, h, i, 0)),
            out_shape=jax.ShapeDtypeStruct((b, nkv, packed, d), q.dtype),
            scratch_shapes=[
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, d), jnp.float32),
            ],
            interpret=interpret,
        )(meta, sink_arr, qt, kt, vt)
    else:
        kernel = functools.partial(
            _flash_kernel,
            block_q=bq,
            block_k=bk,
            num_kv_blocks=t_pad // bk,
            scale=eff_scale,
            rows_per_head=s_pad,
            softcap=softcap,
        )
        out = pl.pallas_call(
            kernel,
            grid=(b, nkv, packed // bq),
            in_specs=[
                pl.BlockSpec((b, 4), lambda bb, h, i: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec((nkv, g), lambda bb, h, i: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, bq, d), lambda bb, h, i: (bb, h, i, 0)),
                pl.BlockSpec((1, 1, t_pad, d), lambda bb, h, i: (bb, h, 0, 0)),
                pl.BlockSpec((1, 1, t_pad, d), lambda bb, h, i: (bb, h, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, bq, d), lambda bb, h, i: (bb, h, i, 0)),
            out_shape=jax.ShapeDtypeStruct((b, nkv, packed, d), q.dtype),
            interpret=interpret,
        )(meta, sink_arr, qt, kt, vt)
    out = out.reshape(b, nkv, g, s_pad, d)[:, :, :, :s, :]
    # [B, Nkv, G, S, D] -> [B, S, Nkv*G(=Nq), D] -> [B, S, Nq*D]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, s, nq * d)


def _paged_decode_kernel(
    tbl_ref,  # SMEM scalar-prefetch [B, MB] int32 — per-lane block chains
    meta_ref,  # SMEM scalar-prefetch [B, 3] int32: (qpos, kv_len, window)
    sink_ref,  # SMEM [Nkv, G] f32 (whole array) — sinks (NEG_INF = none)
    q_ref,  # VMEM [1, 1, g_pad, D] — one (lane, kv head)'s query group
    k_ref,  # VMEM [1, bs, 1, D] — ONE pool block, fetched VIA THE TABLE
    v_ref,  # VMEM [1, bs, 1, D]
    o_ref,  # VMEM [1, 1, g_pad, D]
    m_scr,  # VMEM scratch [g_pad, 1] f32 — running max across chain blocks
    l_scr,  # VMEM scratch [g_pad, 1] f32 — running denominator
    acc_scr,  # VMEM scratch [g_pad, D] f32 — running numerator
    *,
    block_size: int,
    num_chain_blocks: int,  # MB: the (clamped) table width
    g_pad: int,  # G rounded up to the f32 sublane tile
    scale: float,
    softcap: float = 0.0,
):
    """S=1 paged decode attention: walk a lane's block CHAIN with online
    softmax, each K/V block DMA'd straight from its pool slot via the
    scalar-prefetched table (the index map does the indirection) — no
    [B, MB*bs, Nkv, D] dense gather ever exists in HBM, which is the
    whole point vs the XLA sibling (gather_block_kv + decode_gqa). The
    chain axis is the innermost grid axis (TPU grids iterate sequentially,
    row-major), so the online-softmax scratch carry is valid exactly as in
    _flash_kernel_stream. Chain slot j covers absolute positions
    [j*bs, (j+1)*bs) — slot index == absolute position, the PagedKVCache
    layout — so masking is pure positional arithmetic; unallocated table
    entries (scratch block 0) only exist at j >= ceil(kv_len/bs), past the
    `hi` bound, so scratch contents are never even scored."""
    bb = pl.program_id(0)
    hh = pl.program_id(1)
    j = pl.program_id(2)
    qpos = meta_ref[bb, 0]
    kv_len = meta_ref[bb, 1]
    win = meta_ref[bb, 2]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    # causal/validity ceiling and sliding-window floor on the chain walk
    # (same bounds arithmetic as the flash kernels at S == 1): blocks
    # outside [lo, hi) skip their compute entirely
    last = jnp.minimum(kv_len, qpos + 1)
    hi = jnp.clip(pl.cdiv(last, block_size), 0, num_chain_blocks)
    lo_slot = jnp.where(win > 0, qpos - win + 1, 0)
    lo = jnp.clip(lo_slot // block_size, 0, num_chain_blocks)

    @pl.when((j >= lo) & (j < hi))
    def _compute():
        q = q_ref[0, 0]  # [g_pad, D]
        # compressed-KV pools (cfg.kv_dtype): the narrow bytes are what the
        # pipeline fetched; upcast in-register — dequant-fused, in-kernel
        kb = k_ref[0, :, 0, :].astype(q.dtype)  # [bs, D]
        vb = v_ref[0, :, 0, :].astype(q.dtype)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [g_pad, bs]
        s = apply_softcap(s, softcap)
        slot = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (g_pad, block_size), 1
        )  # slot index == absolute position (paged layout)
        mask = (slot < kv_len) & (slot <= qpos)
        mask &= (win <= 0) | (slot > qpos - win)
        s = jnp.where(mask, s, NEG_INF)
        m, l, acc = m_scr[...], l_scr[...], acc_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_scr[...] = m_new
        l_scr[...] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc * alpha + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_chain_blocks - 1)
    def _finalize():
        # row r IS query head hh*g + r here (S == 1), so _fold_sink's
        # packed-row arithmetic degenerates to row_group == row
        # (qi=0, rows_per_head=1); pad rows >= g keep the NEG_INF sink
        rows = jax.lax.broadcasted_iota(jnp.int32, (g_pad, 1), 0)
        l, acc = _fold_sink(
            m_scr[...], l_scr[...], acc_scr[...], sink_ref, hh, 0, rows,
            g_pad, 1,
        )
        out = acc / jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def paged_decode_gqa(
    q: jax.Array,  # [B, 1, Nq, D] — a single-query decode step
    k_pool: jax.Array,  # [NB, bs, Nkv, D] — ONE layer's paged block pool
    v_pool: jax.Array,
    block_table: jax.Array,  # [B, MB] int32 lane -> block chain
    q_positions: jax.Array,  # [B, 1]
    kv_valid_len,  # scalar or [B]
    *,
    scale: Optional[float] = None,
    softcap: float = 0.0,
    window=None,  # traced int32 scalar or None; <= 0 = global
    sinks: Optional[jax.Array] = None,  # [Nq]
    interpret: bool = False,
) -> jax.Array:
    """Pallas paged decode attention — the kernel sibling of
    `gather_block_kv` + `decode_gqa` (same math, no dense gather; see
    _paged_decode_kernel). Returns [B, 1, Nq*D] in q.dtype.

    The block table and the per-lane (qpos, kv_len, window) meta ride as
    SCALAR-PREFETCH operands (pltpu.PrefetchScalarGridSpec), so the
    K/V BlockSpec index maps read `tbl[b, j]` and Pallas pipelines each
    chain block's DMA directly from its pool slot in HBM."""
    b, s, nq, d = q.shape
    if s != 1:
        raise ValueError(f"paged_decode_gqa is S == 1 only, got S={s}")
    bs = k_pool.shape[1]
    nkv = k_pool.shape[2]
    mb = block_table.shape[1]
    g = nq // nkv
    g_pad = _round_up(g, 8)

    # [B, 1, Nq, D] -> [B, Nkv, g_pad, D]: heads nkv*g..nkv*g+g-1 = group
    qt = q.reshape(b, nkv, g, d)
    qt = jnp.pad(qt, ((0, 0), (0, 0), (0, g_pad - g), (0, 0)))

    def as_b(x):
        arr = jnp.asarray(x, jnp.int32)
        return jnp.broadcast_to(arr, (b,)) if arr.ndim == 0 else arr

    win = jnp.int32(0) if window is None else window
    meta = jnp.stack(
        [as_b(q_positions[:, 0]), as_b(kv_valid_len), as_b(win)], axis=1
    )  # [B, 3]
    eff_scale = float(scale) if scale is not None else 1.0 / math.sqrt(d)
    if sinks is None:
        sink_arr = jnp.full((nkv, g), NEG_INF, jnp.float32)
    else:
        sink_arr = sinks.astype(jnp.float32).reshape(nkv, g)

    kernel = functools.partial(
        _paged_decode_kernel,
        block_size=bs,
        num_chain_blocks=mb,
        g_pad=g_pad,
        scale=eff_scale,
        softcap=softcap,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nkv, mb),
        in_specs=[
            pl.BlockSpec(
                (nkv, g), lambda bb, h, j, tbl, meta: (0, 0),
                memory_space=pltpu.SMEM,
            ),
            pl.BlockSpec(
                (1, 1, g_pad, d), lambda bb, h, j, tbl, meta: (bb, h, 0, 0)
            ),
            pl.BlockSpec(
                (1, bs, 1, d),
                lambda bb, h, j, tbl, meta: (tbl[bb, j], 0, h, 0),
            ),
            pl.BlockSpec(
                (1, bs, 1, d),
                lambda bb, h, j, tbl, meta: (tbl[bb, j], 0, h, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g_pad, d), lambda bb, h, j, tbl, meta: (bb, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((g_pad, 1), jnp.float32),
            pltpu.VMEM((g_pad, 1), jnp.float32),
            pltpu.VMEM((g_pad, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nkv, g_pad, d), q.dtype),
        interpret=interpret,
    )(block_table.astype(jnp.int32), meta, sink_arr, qt, k_pool, v_pool)
    # [B, Nkv, g_pad, D] -> [B, Nkv, G, D] -> [B, 1, Nq*D]
    return out[:, :, :g, :].reshape(b, 1, nq * d)


def decode_gqa(
    q: jax.Array,  # [B, 1, Nq, D] — a single-query decode step
    k: jax.Array,  # [B, T, Nkv, D] — kv buffer, possibly compressed dtype
    v: jax.Array,  # [B, T, Nkv, D]
    q_positions: jax.Array,  # [B, 1]
    kv_valid_len,  # scalar or [B]
    kv_positions: Optional[jax.Array] = None,  # [B, T] or [T]
    scale: Optional[float] = None,
    softcap: float = 0.0,
    window=None,  # traced int32 scalar or None; <= 0 = global
    sinks: Optional[jax.Array] = None,  # [Nq]
    block_table: Optional[jax.Array] = None,  # [B, MB] — k/v are then
    #   PAGED POOLS [NB, bs, Nkv, D] read through the table (gather_block_kv)
) -> jax.Array:
    """Single-query (S == 1) GQA decode fast path — the `lax`-composite
    sibling of the Pallas kernels, and the path `auto` dispatch serves
    decode steps on CPU/XLA.

    With `block_table`, k/v are paged block pools and the read gathers
    through the table first (gather_block_kv) — exact vs the dense path
    by construction (the gathered view is position-contiguous), including
    compressed-KV layouts (the gather preserves the narrow dtype, so the
    upcast stays dequant-fused in the contraction operand stream below).

    Identical math to models/qwen3.gqa_attention at S == 1 with the query
    axis dropped from every intermediate: scores are [B, Nkv, G, T] (not
    [B, Nkv, G, 1, T]), the mask is [B, T], and softmax runs over the one
    real axis — no S-broadcast tensors, fewer transposes. For compressed
    KV layouts (cfg.kv_dtype narrower than the activations — fp8 today)
    the upcast is DEQUANT-FUSED: it sits element-wise in the score/output
    contractions' operand stream (the same contract as weight-dequant
    QDOT_MODE), so XLA reads the narrow bytes from HBM and widens
    in-register instead of materializing a full-width copy of the cache.

    Shares apply_softcap / the window boundary convention with the
    general path so the numerics cannot drift between S == 1 and S > 1.
    """
    if block_table is not None:
        # paged decode dispatch: the Pallas chain-walk kernel when this
        # chip MEASURED it winning (autotune registry / FORCE_PAGED_KERNEL
        # test hook); cold registry -> the XLA gather path, bit-for-bit
        if kv_positions is None and paged_kernel_enabled():
            return paged_decode_gqa(
                q, k, v, block_table, q_positions, kv_valid_len,
                scale=scale, softcap=softcap, window=window, sinks=sinks,
                interpret=not is_tpu(),
            )
        k, v = gather_block_kv(k, v, block_table)
    b, s, nq, d = q.shape
    t, nkv = k.shape[1], k.shape[2]
    g = nq // nkv
    qh = q.reshape(b, nkv, g, d)  # s == 1: drop the query axis
    # dequant-fused upcast: adjacent to the dot, widened in its operand
    # stream (never a standalone [B, T, Nkv, D] full-width buffer)
    scores = jnp.einsum(
        "bngd,btnd->bngt", qh, k.astype(q.dtype)
    ).astype(jnp.float32)
    scores = scores * (float(scale) if scale is not None else 1.0 / math.sqrt(d))
    scores = apply_softcap(scores, softcap)

    slots = jnp.arange(t)
    valid = jnp.asarray(kv_valid_len)
    if valid.ndim == 0:
        valid = valid[None]
    kpos = slots if kv_positions is None else kv_positions
    if kpos.ndim == 1:
        kpos = kpos[None, :]
    qpos = q_positions[:, 0]  # [B]
    mask = (slots[None, :] < valid[:, None]) & (kpos <= qpos[:, None])  # [B, T]
    # shared sliding-window predicate (apply_window_mask is THE single
    # definition of the boundary convention) over the S=1 mask
    mask = apply_window_mask(mask[:, None, :], kpos, qpos[:, None], window)[:, 0]
    scores = jnp.where(mask[:, None, None, :], scores, jnp.float32(NEG_INF))
    if sinks is not None:
        # per-q-head sink logit joins the softmax denominator (the exact
        # closed form gqa_attention uses)
        sk = sinks.astype(jnp.float32).reshape(nkv, g)[None, :, :, None]
        m = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), sk)
        p = jnp.exp(scores - m)
        denom = jnp.sum(p, axis=-1, keepdims=True) + jnp.exp(sk - m)
        probs = (p / denom).astype(q.dtype)
    else:
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bngt,btnd->bngd", probs, v.astype(q.dtype))
    return out.reshape(b, 1, nq * d)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

# Test hook: None = decide from cfg.attn_impl + backend; True/False = force.
FORCE_FLASH: Optional[bool] = None

# Test hook for the paged decode kernel: None = consult the autotune
# registry (cold -> the XLA gather path); True/False = force.
FORCE_PAGED_KERNEL: Optional[bool] = None


def paged_kernel_enabled() -> bool:
    """Route paged decode (decode_gqa with a block table) through the
    Pallas chain-walk kernel? Measured-not-assumed: only when the autotune
    registry (perf/autotune.py, populated by `tools/sweep_attn --kernels`)
    recorded the kernel WINNING on this chip — a cold registry keeps the
    XLA gather path byte-identical to before the kernel existed."""
    if FORCE_PAGED_KERNEL is not None:
        return FORCE_PAGED_KERNEL
    from inferd_tpu.perf import autotune

    return autotune.paged_decode_winner() == "kernel"

# `auto` routes to the streaming kernel only when the XLA path's score
# materialization ([B, Nq, S, T] f32) would exceed this budget. Measured on a
# real v5e (round 2 sweep, in-graph chained timing): XLA attention meets or
# beats both Pallas kernels at every decode (S=1, T 2K-32K) and moderate
# prefill (S=T 512-4096) shape — XLA's own fusion already runs these
# bandwidth-bound — so the kernels' structural win is MEMORY at large S*T
# (long-prompt prefill over a long cache), where the XLA path's score tensor
# stops fitting. Sweep: sweep results in BASELINE.md "attention dispatch".
_XLA_SCORE_BUDGET = 256 * 1024 * 1024


def flash_enabled(
    cfg,
    kv_buf_len: int,
    compressed_kv: bool = False,
    q_len: int = 1,
    batch: int = 1,
) -> bool:
    """Should the model use the Pallas kernel for this attention call?

    `auto` is measurement-driven (see _XLA_SCORE_BUDGET): XLA for every
    shape where its fused attention wins on hardware, the streaming Pallas
    kernel when score materialization would exceed the budget — so
    long-context prefill never OOMs and never falls back to a multi-GB
    score tensor (the reference's weakness, qwen3_server_module.py:67-89,
    and round-1 VERDICT A6's cap, both remain dead).
    `flash`/`flash_interpret` force the kernels (interpret runs in the
    Pallas interpreter — CPU-testable); `xla` forces the jnp path.

    compressed_kv: the KV buffer is stored narrower than the activations
    (cfg.kv_dtype). The kernels upcast in VMEM after the block fetch (the
    structural half-read), but Mosaic's narrow-float load support varies by
    TPU generation — so `auto` keeps compressed KV on the XLA path (where
    the upcast fuses into the score einsum) and the kernel route is the
    explicit impls / FORCE_FLASH only.
    """
    if FORCE_FLASH is not None:
        return FORCE_FLASH
    impl = getattr(cfg, "attn_impl", "auto")
    if impl in ("flash", "flash_interpret"):
        return True
    if impl != "auto":
        return False
    # Measured-on-THIS-chip dispatch: when the autotune registry
    # (perf/autotune.py, populated by `tools/sweep_attn --populate`) has a
    # winner recorded for this (chip, shape, dtype) bucket, it overrides
    # the frozen heuristics below — including the compressed-KV caution,
    # which is exactly the case a measurement should decide (VERDICT r05
    # weak #3: the fp8-KV flash path never runs under the frozen rule).
    # Cold registry -> the heuristics below, bit-for-bit.
    from inferd_tpu.perf import autotune

    measured = autotune.attn_winner(
        cfg, kv_buf_len, q_len=q_len, batch=batch, compressed=compressed_kv
    )
    if measured is not None:
        return measured == "flash"
    if compressed_kv:
        return False
    if not is_tpu():
        return False
    score_bytes = 4 * batch * cfg.num_heads * q_len * kv_buf_len
    return score_bytes > _XLA_SCORE_BUDGET


def flash_interpret(cfg) -> bool:
    """Run the kernel in the Pallas interpreter? Always off TPU (where the
    Mosaic compiler is unavailable), and on explicit request."""
    return getattr(cfg, "attn_impl", "auto") == "flash_interpret" or (
        not is_tpu()
    )
