"""LoRA adapters: load peft-format safetensors and merge into base params.

Merged serving: W' = W + (alpha/r) * A @ B, applied at LOAD time, before
quantization — so every engine, executor, mesh mode, and quant level serves
the adapted weights with zero runtime overhead. That is the TPU-first
choice for single-adapter deployments: no extra matmuls in the decode hot
path, no per-layer dispatch, and the merged weights quantize/shard exactly
like the base checkpoint. (Per-request multi-adapter batching a la S-LoRA
is out of scope; a merged adapter composes with everything that exists.)

The reference has no fine-tuning/adapter story at all (SURVEY §2) — this is
added TPU-native scope. File format: HF peft `adapter_model.safetensors` +
`adapter_config.json` (lora_alpha, r), parameter names like
`base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight`.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from inferd_tpu.config import ModelConfig
from inferd_tpu.models.loader import _to_np

Params = Dict[str, Any]

# decoder-layer leaves an adapter may target (stacked [L, in, out] weights)
TARGETS = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)

_KEY_RE = re.compile(
    r"layers\.(\d+)\.(?:self_attn|mlp)\.(\w+)\.lora_(A|B)\.(?:\w+\.)?weight$"
)


def adapter_from_state_dict(
    cfg: ModelConfig, sd, alpha: float, r: int, rslora: bool = False
) -> Dict[str, Any]:
    """Parse a peft state dict into {"layers": {name: (A, B)}, "scale"}.

    A is stacked [L, in, r], B is [L, r, out] (peft stores lora_A [r, in]
    and lora_B [out, r]; we transpose into the x @ W convention). Every
    targeted projection must be present for ALL layers — peft applies
    adapters uniformly, so a gap means a config mismatch, not a choice.
    Any lora_A/lora_B key OUTSIDE the supported decoder-layer targets
    (lm_head, embeddings, modules_to_save, MoE experts) is an error —
    silently dropping it would serve a partially-adapted model.
    """
    found: Dict[str, Dict[int, Dict[str, np.ndarray]]] = {}
    matched = 0
    for key, val in sd.items():
        m = _KEY_RE.search(key)
        if m is None:
            if "lora_A" in key or "lora_B" in key:
                raise ValueError(
                    f"LoRA adapter parameter {key!r} targets a module "
                    f"outside the supported decoder-layer projections "
                    f"{TARGETS} — refusing to serve a partially-adapted model"
                )
            continue
        i, name, ab = int(m.group(1)), m.group(2), m.group(3)
        if name not in TARGETS:
            raise ValueError(
                f"LoRA adapter targets unsupported module {name!r} "
                f"(supported: {TARGETS})"
            )
        found.setdefault(name, {}).setdefault(i, {})[ab] = _to_np(val)
        matched += 1
    if not matched:
        raise ValueError("no LoRA parameters found in adapter state dict")

    layers: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]] = {}
    for name, per_layer in found.items():
        beyond = [i for i in per_layer if i >= cfg.num_layers]
        if beyond:
            raise ValueError(
                f"LoRA adapter has layers {sorted(beyond)} for {name!r} but "
                f"the model has only {cfg.num_layers} layers — wrong adapter "
                f"for this model"
            )
        missing = [i for i in range(cfg.num_layers) if i not in per_layer]
        if missing:
            raise ValueError(
                f"LoRA adapter misses layers {missing} for {name!r} "
                f"(model has {cfg.num_layers} layers)"
            )
        halves = [
            (i, ab)
            for i in range(cfg.num_layers)
            for ab in ("A", "B")
            if ab not in per_layer[i]
        ]
        if halves:
            raise ValueError(
                f"LoRA adapter is missing matrices for {name!r}: "
                + ", ".join(f"layer {i} lora_{ab}" for i, ab in halves)
            )
        a = np.stack([per_layer[i]["A"].T for i in range(cfg.num_layers)])
        b = np.stack([per_layer[i]["B"].T for i in range(cfg.num_layers)])
        if a.shape[-1] != r or b.shape[1] != r:
            raise ValueError(
                f"LoRA rank mismatch for {name!r}: A{a.shape} B{b.shape} vs r={r}"
            )
        layers[name] = (jnp.asarray(a), jnp.asarray(b))
    # rsLoRA (arXiv:2312.03732) scales alpha/sqrt(r) instead of alpha/r
    scale = float(alpha) / (float(r) ** 0.5 if rslora else float(r))
    return {"layers": layers, "scale": scale}


def load_adapter(cfg: ModelConfig, path: str) -> Dict[str, Any]:
    """Load a peft adapter directory (adapter_config.json + safetensors)."""
    cfg_path = os.path.join(path, "adapter_config.json")
    with open(cfg_path) as f:
        acfg = json.load(f)
    alpha, r = float(acfg["lora_alpha"]), int(acfg["r"])
    from safetensors import safe_open

    sd: Dict[str, Any] = {}
    fname = os.path.join(path, "adapter_model.safetensors")
    with safe_open(fname, framework="np") as f:
        for k in f.keys():
            sd[k] = f.get_tensor(k)
    return adapter_from_state_dict(
        cfg, sd, alpha, r, rslora=bool(acfg.get("use_rslora", False))
    )


def slice_adapter(adapter: Dict[str, Any], start: int, end: int) -> Dict[str, Any]:
    """Adapter restricted to layers [start, end) — mirrors
    models.qwen3.slice_layers so per-stage checkpoints merge their slice."""
    return {
        "layers": {
            name: (a[start:end], b[start:end])
            for name, (a, b) in adapter["layers"].items()
        },
        "scale": adapter["scale"],
    }


def merge_adapter(params: Params, adapter: Dict[str, Any]) -> Params:
    """W' = W + scale * A @ B per targeted leaf; float32 accumulate, cast
    back to the weight dtype. Leaves untouched by the adapter (norms, MoE
    experts, embed/head) pass through unchanged."""
    layers = dict(params["layers"])
    scale = adapter["scale"]
    for name, (a, b) in adapter["layers"].items():
        if name not in layers:
            raise ValueError(f"adapter targets {name!r} absent from params")
        w = layers[name]
        if w.ndim != 3:
            raise ValueError(
                f"adapter target {name!r} is not a stacked [L, in, out] "
                f"weight (MoE expert adapters are unsupported)"
            )
        delta = scale * jnp.einsum(
            "lir,lro->lio",
            a.astype(jnp.float32),
            b.astype(jnp.float32),
        )
        layers[name] = (w.astype(jnp.float32) + delta).astype(w.dtype)
    out = dict(params)
    out["layers"] = layers
    return out
