"""LoRA adapters: load peft-format safetensors; merge OR batch-apply.

Two serving modes, strictly exclusive per node (one merged path xor the
registry — `check_exclusive_modes`):

  * MERGED (`run_node --lora DIR`): W' = W + (alpha/r) * A @ B, applied at
    LOAD time, before quantization — every engine, executor, mesh mode,
    and quant level serves the adapted weights with zero runtime overhead.
    The TPU-first choice for single-adapter deployments.
  * BATCHED UNMERGED (`run_node --adapters DIR[,DIR...]`, S-LoRA-style —
    Sheng et al.; Punica, Chen et al.): the base weights stay pristine and
    per-lane int32 adapter ids gather stacked device pools inside the
    co-batched stage forward: y += scale[id] * (x @ A[id]) @ B[id]
    (`lane_delta` below, wired through models/qwen3.decoder_layer). One
    dispatch serves a heterogeneous-adapter window; tenants share the base
    model instead of each demanding a dedicated merged replica. Pools and
    hot-load/evict live in runtime/adapters.AdapterRegistry.

The reference has no fine-tuning/adapter story at all (SURVEY §2) — this is
added TPU-native scope. File format: HF peft `adapter_model.safetensors` +
`adapter_config.json` (lora_alpha, r), parameter names like
`base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight`.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from inferd_tpu.utils.platform import is_tpu

from inferd_tpu.config import ModelConfig
from inferd_tpu.models.loader import _to_np

Params = Dict[str, Any]

# decoder-layer leaves an adapter may target (stacked [L, in, out] weights)
TARGETS = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)

_KEY_RE = re.compile(
    r"layers\.(\d+)\.(?:self_attn|mlp)\.(\w+)\.lora_(A|B)\.(?:\w+\.)?weight$"
)


def adapter_from_state_dict(
    cfg: ModelConfig, sd, alpha: float, r: int, rslora: bool = False
) -> Dict[str, Any]:
    """Parse a peft state dict into {"layers": {name: (A, B)}, "scale"}.

    A is stacked [L, in, r], B is [L, r, out] (peft stores lora_A [r, in]
    and lora_B [out, r]; we transpose into the x @ W convention). Every
    targeted projection must be present for ALL layers — peft applies
    adapters uniformly, so a gap means a config mismatch, not a choice.
    Any lora_A/lora_B key OUTSIDE the supported decoder-layer targets
    (lm_head, embeddings, modules_to_save, MoE experts) is an error —
    silently dropping it would serve a partially-adapted model.
    """
    found: Dict[str, Dict[int, Dict[str, np.ndarray]]] = {}
    matched = 0
    for key, val in sd.items():
        m = _KEY_RE.search(key)
        if m is None:
            if "lora_A" in key or "lora_B" in key:
                raise ValueError(
                    f"LoRA adapter parameter {key!r} targets a module "
                    f"outside the supported decoder-layer projections "
                    f"{TARGETS} — refusing to serve a partially-adapted model"
                )
            continue
        i, name, ab = int(m.group(1)), m.group(2), m.group(3)
        if name not in TARGETS:
            raise ValueError(
                f"LoRA adapter targets unsupported module {name!r} "
                f"(supported: {TARGETS})"
            )
        found.setdefault(name, {}).setdefault(i, {})[ab] = _to_np(val)
        matched += 1
    if not matched:
        raise ValueError("no LoRA parameters found in adapter state dict")

    layers: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]] = {}
    for name, per_layer in found.items():
        beyond = [i for i in per_layer if i >= cfg.num_layers]
        if beyond:
            raise ValueError(
                f"LoRA adapter has layers {sorted(beyond)} for {name!r} but "
                f"the model has only {cfg.num_layers} layers — wrong adapter "
                f"for this model"
            )
        missing = [i for i in range(cfg.num_layers) if i not in per_layer]
        if missing:
            raise ValueError(
                f"LoRA adapter misses layers {missing} for {name!r} "
                f"(model has {cfg.num_layers} layers)"
            )
        halves = [
            (i, ab)
            for i in range(cfg.num_layers)
            for ab in ("A", "B")
            if ab not in per_layer[i]
        ]
        if halves:
            raise ValueError(
                f"LoRA adapter is missing matrices for {name!r}: "
                + ", ".join(f"layer {i} lora_{ab}" for i, ab in halves)
            )
        a = np.stack([per_layer[i]["A"].T for i in range(cfg.num_layers)])
        b = np.stack([per_layer[i]["B"].T for i in range(cfg.num_layers)])
        if a.shape[-1] != r or b.shape[1] != r:
            raise ValueError(
                f"LoRA rank mismatch for {name!r}: A{a.shape} B{b.shape} vs r={r}"
            )
        layers[name] = (jnp.asarray(a), jnp.asarray(b))
    # rsLoRA (arXiv:2312.03732) scales alpha/sqrt(r) instead of alpha/r
    scale = float(alpha) / (float(r) ** 0.5 if rslora else float(r))
    return {"layers": layers, "scale": scale}


def load_adapter(cfg: ModelConfig, path: str) -> Dict[str, Any]:
    """Load a peft adapter directory (adapter_config.json + safetensors)."""
    cfg_path = os.path.join(path, "adapter_config.json")
    with open(cfg_path) as f:
        acfg = json.load(f)
    alpha, r = float(acfg["lora_alpha"]), int(acfg["r"])
    from safetensors import safe_open

    sd: Dict[str, Any] = {}
    fname = os.path.join(path, "adapter_model.safetensors")
    with safe_open(fname, framework="np") as f:
        for k in f.keys():
            sd[k] = f.get_tensor(k)
    return adapter_from_state_dict(
        cfg, sd, alpha, r, rslora=bool(acfg.get("use_rslora", False))
    )


def check_exclusive_modes(lora: Any, adapters: Any, owner: str = "node") -> None:
    """LOUD mutual exclusion between the merged path (`--lora`) and the
    multi-tenant registry (`--adapters`): merged weights already CONTAIN
    one adapter, so stacking per-lane deltas on top would serve every
    tenant a sum of two adapters — never what anyone asked for. One
    merged path xor the registry; silent pass-through is forbidden."""
    if lora and adapters:
        raise ValueError(
            f"{owner}: --lora (merge ONE adapter into the weights) and "
            f"--adapters (multi-tenant batched registry) are mutually "
            f"exclusive — merged weights plus per-lane deltas would serve "
            f"every tenant two adapters; pick one mode"
        )


def save_adapter(
    path: str,
    layers: Dict[str, Tuple[Any, Any]],
    alpha: float,
    r: int,
    rslora: bool = False,
) -> str:
    """Write stacked {name: (A [L, in, r], B [L, r, out])} matrices as a
    peft-format adapter directory (the exact inverse of load_adapter:
    peft stores lora_A [r, in] / lora_B [out, r] per layer) — the
    synthetic-tenant scaffolding the multi-adapter bench and tests build
    their catalogs with."""
    from safetensors.numpy import save_file

    sd: Dict[str, Any] = {}
    for name, (a, b) in layers.items():
        mod = (
            "self_attn"
            if name in ("q_proj", "k_proj", "v_proj", "o_proj") else "mlp"
        )
        for i in range(a.shape[0]):
            pre = f"base_model.model.model.layers.{i}.{mod}.{name}"
            sd[f"{pre}.lora_A.weight"] = np.ascontiguousarray(
                np.asarray(a[i], np.float32).T
            )
            sd[f"{pre}.lora_B.weight"] = np.ascontiguousarray(
                np.asarray(b[i], np.float32).T
            )
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "adapter_config.json"), "w") as f:
        json.dump(
            {"lora_alpha": alpha, "r": int(r), "use_rslora": bool(rslora)},
            f,
        )
    save_file(sd, os.path.join(path, "adapter_model.safetensors"))
    return path


def slice_adapter(
    adapter: Dict[str, Any], start: int, end: int, owner: str = "",
) -> Dict[str, Any]:
    """Adapter restricted to layers [start, end) — mirrors
    models.qwen3.slice_layers so per-stage checkpoints merge their slice.

    Bounds are validated against the adapter's stacked layer count: an
    empty or out-of-range slice would silently merge as a NO-OP (an
    empty-layer adapter applies nothing), serving the base model to a
    tenant who asked for their fine-tune — `owner` (the stage identity)
    rides the error so a misconfigured stage names itself."""
    who = f"{owner}: " if owner else ""
    if start < 0 or start >= end:
        raise ValueError(
            f"{who}adapter slice [{start}, {end}) is empty or inverted — "
            f"an empty-layer adapter would merge as a silent no-op"
        )
    n_layers = min(
        a.shape[0] for a, _b in adapter["layers"].values()
    ) if adapter["layers"] else 0
    if end > n_layers:
        raise ValueError(
            f"{who}adapter slice [{start}, {end}) runs past the adapter's "
            f"{n_layers} stacked layers — wrong stage spec for this adapter"
        )
    return {
        "layers": {
            name: (a[start:end], b[start:end])
            for name, (a, b) in adapter["layers"].items()
        },
        "scale": adapter["scale"],
    }


def merge_adapter(params: Params, adapter: Dict[str, Any]) -> Params:
    """W' = W + scale * A @ B per targeted leaf; float32 accumulate, cast
    back to the weight dtype. Leaves untouched by the adapter (norms, MoE
    experts, embed/head) pass through unchanged."""
    layers = dict(params["layers"])
    scale = adapter["scale"]
    for name, (a, b) in adapter["layers"].items():
        if name not in layers:
            raise ValueError(f"adapter targets {name!r} absent from params")
        w = layers[name]
        if w.ndim != 3:
            raise ValueError(
                f"adapter target {name!r} is not a stacked [L, in, out] "
                f"weight (MoE expert adapters are unsupported)"
            )
        delta = scale * jnp.einsum(
            "lir,lro->lio",
            a.astype(jnp.float32),
            b.astype(jnp.float32),
        )
        layers[name] = (w.astype(jnp.float32) + delta).astype(w.dtype)
    out = dict(params)
    out["layers"] = layers
    return out


# ---------------------------------------------------------------------------
# Batched unmerged apply (the multi-tenant registry's device math)
# ---------------------------------------------------------------------------
#
# Pool pytree contract (runtime/adapters.AdapterRegistry.device_adapters +
# the executor's per-dispatch lane ids): an `adapters` operand handed to the
# batched forwards is
#
#   {"a":     {target: [slots, L, in, r]},   # slot 0 = zero "base" adapter
#    "b":     {target: [slots, L, r, out]},
#    "scale": [slots] float32,               # alpha/r (or alpha/sqrt(r))
#    "ids":   [B] int32}                     # per-lane slot, jit-visible
#
# like the paged block TABLE, `ids` is an ordinary array operand: ONE
# compiled program serves every adapter-to-lane assignment, and a window
# mixing tenants co-batches in one dispatch.


def gather_lanes(adapters: Dict[str, Any]):
    """Per-lane gather of the stacked pools, done ONCE per dispatch:
    ({target: (a [L, B, in, r], b [L, B, r, out])}, scale [B] f32) — the
    layer-leading layout rides a lax.scan over stacked layers
    (models/qwen3.forward_layers) exactly like the KV buffers do."""
    ids = adapters["ids"]
    per = {
        name: (
            jnp.swapaxes(adapters["a"][name][ids], 0, 1),
            jnp.swapaxes(adapters["b"][name][ids], 0, 1),
        )
        for name in adapters["a"]
    }
    return per, adapters["scale"].astype(jnp.float32)[ids]


def lane_delta(
    x: jnp.ndarray,  # [B, S, in] projection input
    a: jnp.ndarray,  # [B, in, r] this layer's per-lane A
    b: jnp.ndarray,  # [B, r, out] this layer's per-lane B
    scale: jnp.ndarray,  # [B] f32
) -> jnp.ndarray:
    """scale[lane] * (x @ A[lane]) @ B[lane] -> [B, S, out] float32.

    Two thin matmuls through the rank bottleneck instead of materializing
    any [in, out] delta (the S-LoRA/Punica shape); float32 accumulation
    mirrors merge_adapter so the unmerged path tracks the merged one to
    rounding, and slot 0's all-zero A/B make base-adapter lanes an exact
    no-op."""
    xa = jnp.einsum("bsi,bir->bsr", x.astype(jnp.float32), a.astype(jnp.float32))
    d = jnp.einsum("bsr,bro->bso", xa, b.astype(jnp.float32))
    return d * scale[:, None, None]


def _fused_delta_kernel(
    ids_ref,  # [B] int32 per-lane slot ids (scalar-prefetch, SMEM)
    lay_ref,  # [1] int32 current stacked-layer index (scalar-prefetch)
    scale_ref,  # [1, slots] f32 per-slot scales (SMEM, read whole)
    x_ref,  # [1, S, in] this lane's projection input
    a_ref,  # [1, 1, in, r] pool block: THIS lane's slot, THIS layer
    b_ref,  # [1, 1, r, out]
    o_ref,  # [1, S, out] f32
):
    """scale[ids[lane]] * (x @ A[ids[lane], layer]) @ B[...] for one lane.
    The pool indexing happens in the BlockSpec index maps (scalar-prefetch
    ids pick which [in, r]/[r, out] block the pipeline fetches), so only
    each lane's OWN adapter crosses HBM — the XLA sibling's gather_lanes
    materializes the full [B, L, in, r] per-lane copy per dispatch. f32
    accumulation end-to-end, mirroring lane_delta exactly."""
    bb = pl.program_id(0)
    x = x_ref[0].astype(jnp.float32)  # [S, in]
    a = a_ref[0, 0].astype(jnp.float32)  # [in, r]
    b = b_ref[0, 0].astype(jnp.float32)  # [r, out]
    xa = jax.lax.dot_general(
        x, a, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    d = jax.lax.dot_general(
        xa, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    o_ref[0] = d * scale_ref[0, ids_ref[bb]]


def fused_lane_delta(
    x: jnp.ndarray,  # [B, S, in] projection input
    a_pool: jnp.ndarray,  # [slots, L, in, r] stacked A pool (one target)
    b_pool: jnp.ndarray,  # [slots, L, r, out]
    scale_pool: jnp.ndarray,  # [slots] f32
    ids: jnp.ndarray,  # [B] int32 per-lane slot ids
    layer: jnp.ndarray,  # scalar int32 stacked-layer index (scan carry)
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused replacement for gather_lanes + lane_delta at ONE projection of
    ONE layer: slot ids index the stacked pools in-kernel, so the gathered
    per-lane [B, L, in, r] copies never exist. Returns [B, S, out] f32 —
    the same delta lane_delta produces (slot 0's zero A/B still make base
    lanes an exact no-op)."""
    bsz, s, d_in = x.shape
    slots, n_layers, _, r = a_pool.shape
    d_out = b_pool.shape[-1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bsz,),
        in_specs=[
            pl.BlockSpec(
                (1, slots), lambda bb, ids, lay: (0, 0),
                memory_space=pltpu.SMEM,
            ),
            pl.BlockSpec((1, s, d_in), lambda bb, ids, lay: (bb, 0, 0)),
            pl.BlockSpec(
                (1, 1, d_in, r), lambda bb, ids, lay: (ids[bb], lay[0], 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, r, d_out), lambda bb, ids, lay: (ids[bb], lay[0], 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, s, d_out), lambda bb, ids, lay: (bb, 0, 0)
        ),
    )
    return pl.pallas_call(
        _fused_delta_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bsz, s, d_out), jnp.float32),
        interpret=interpret,
    )(
        ids.astype(jnp.int32),
        jnp.asarray(layer, jnp.int32).reshape(1),
        scale_pool.astype(jnp.float32)[None, :],
        x, a_pool, b_pool,
    )


# Whether the batched forwards route adapter deltas through the fused
# kernel (skipping gather_lanes entirely) instead of the gather + einsum
# path. None -> consult the autotune registry's measured verdict
# (perf/autotune.lora_delta_winner); cold registry -> the XLA path,
# byte-identical. Tests force either side deterministically.
FORCE_LORA_KERNEL: Optional[bool] = None


def fused_delta_enabled() -> bool:
    if FORCE_LORA_KERNEL is not None:
        return FORCE_LORA_KERNEL
    from inferd_tpu.perf import autotune

    return autotune.lora_delta_winner() == "kernel"


def apply_lane_delta(y: jnp.ndarray, x: jnp.ndarray, name: str,
                     lane_adapters: Optional[Dict[str, Any]]) -> jnp.ndarray:
    """y (the base projection output for `name`) plus this layer's
    per-lane LoRA delta; pass-through when the window carries no adapters
    or the pools don't cover this target. The ONE application site shared
    by every projection in models/qwen3.decoder_layer.

    Two lane_adapters forms arrive here (models/qwen3.forward_layers
    builds whichever dispatch picked):
      * {"layers": {name: (a [B, in, r], b [B, r, out])}, "scale": [B]} —
        the pre-gathered per-layer slices riding the scan (XLA path);
      * {"pools": <stacked pool pytree>, "layer": int32 scalar} — the
        fused-kernel path: the full pools plus this scan step's layer
        index, gathered in-kernel by fused_lane_delta."""
    if lane_adapters is None:
        return y
    if "pools" in lane_adapters:
        pools = lane_adapters["pools"]
        if name not in pools["a"]:
            return y
        d = fused_lane_delta(
            x, pools["a"][name], pools["b"][name], pools["scale"],
            pools["ids"], lane_adapters["layer"], interpret=not is_tpu(),
        )
        return (y.astype(jnp.float32) + d).astype(y.dtype)
    ab = lane_adapters["layers"].get(name)
    if ab is None:
        return y
    d = lane_delta(x, ab[0], ab[1], lane_adapters["scale"])
    return (y.astype(jnp.float32) + d).astype(y.dtype)
