"""Stage executor: jitted per-stage forward with per-session KV caches.

The compute half of a node. Capability parity with the reference's
`Qwen3Server.send` (/root/reference/models/qwen3/server/
qwen3_server_module.py:237-255 — run my layer range with a per-session
DynamicCache) and `PartitionedQwen2.forward` (/root/reference/petals/
partitioned_models.py:145-168 — first/inner/last stage dispatch), redesigned:

  * functional preallocated KV caches per session (static shapes for jit),
    bucket-grown on demand, LRU-evicted;
  * prompt chunks padded to power-of-two buckets so XLA compiles once per
    bucket instead of once per length;
  * RoPE is computed from absolute positions inside the stage, so the wire
    carries only (tokens|hidden, start_pos) — not cos/sin/mask tensors like
    the reference's 5-tensor gRPC payload (rpc_client.py:47-54).

Thread-safety: process() is called from a worker thread pool (the node keeps
compute off its event loop — fixing reference bug B5); a per-session lock
serializes steps of one session while different sessions run concurrently.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from inferd_tpu.config import ModelConfig
from inferd_tpu.core.cache import RING_MARGIN, KVCache, grow
from inferd_tpu.core.generate import bucket_len
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.stages import StageSpec


class SessionStore:
    """session_id -> KVCache with LRU eviction and idle TTL."""

    def __init__(self, max_sessions: int = 64, ttl_s: float = 600.0):
        self.max_sessions = max_sessions
        self.ttl_s = ttl_s
        self._lock = threading.Lock()
        self._caches: Dict[str, KVCache] = {}
        self._locks: Dict[str, threading.Lock] = {}
        self._last_used: Dict[str, float] = {}

    def lock_for(self, session_id: str) -> threading.Lock:
        with self._lock:
            if session_id not in self._locks:
                self._locks[session_id] = threading.Lock()
            return self._locks[session_id]

    def get(self, session_id: str) -> Optional[KVCache]:
        with self._lock:
            c = self._caches.get(session_id)
            if c is not None:
                self._last_used[session_id] = time.monotonic()
            return c

    def put(self, session_id: str, cache: KVCache) -> None:
        with self._lock:
            self._caches[session_id] = cache
            self._last_used[session_id] = time.monotonic()
            self._evict_locked()

    def drop(self, session_id: str) -> None:
        with self._lock:
            self._caches.pop(session_id, None)
            self._locks.pop(session_id, None)
            self._last_used.pop(session_id, None)

    def items_snapshot(self):
        """Point-in-time [(session_id, cache)] — for migration export."""
        with self._lock:
            return list(self._caches.items())

    def kv_bytes(self) -> int:
        """Total bytes of live session KV buffers — the node's /metrics
        `kv.bytes` gauge (capacity-planning observability)."""
        total = 0
        for _sid, c in self.items_snapshot():
            for arr in (c.k, c.v, c.k_loc, c.v_loc):
                total += int(getattr(arr, "nbytes", 0) or 0)
        return total

    def sweep(self) -> int:
        """Drop sessions idle for > ttl_s; returns count dropped."""
        now = time.monotonic()
        with self._lock:
            stale = [s for s, t in self._last_used.items() if now - t > self.ttl_s]
            for s in stale:
                self._caches.pop(s, None)
                self._locks.pop(s, None)
                self._last_used.pop(s, None)
            return len(stale)

    def __len__(self) -> int:
        with self._lock:
            return len(self._caches)

    def __contains__(self, session_id: str) -> bool:
        with self._lock:
            return session_id in self._caches

    def ids(self):
        """Live session ids (for the gossip session-location advertising,
        runtime/node.py announce)."""
        with self._lock:
            return list(self._caches)

    def _evict_locked(self) -> None:
        while len(self._caches) > self.max_sessions:
            oldest = min(self._last_used, key=self._last_used.get)
            self._caches.pop(oldest, None)
            self._locks.pop(oldest, None)
            self._last_used.pop(oldest, None)


def parse_kstep(payload: Dict[str, Any], budget: int):
    """Parse a multi-step fused-decode request out of a /forward payload,
    shared by all three executors (solo/batched/stage-batch) so the wire
    contract cannot drift.

    Payload keys: "decode_steps" (requested K), optional "sampling"
    ({temperature, top_k, top_p, min_p} — greedy default), optional "eos"
    (stop token id; absent = none), optional "key" ([2] uint32 per-session
    PRNG chain) / "seed" (derives the chain's root when no key rides yet).

    Returns None when the payload requests no multi-step decode, else
    {"k": K clamped into [1, budget] (falling back toward K=1 at budget
    boundaries so the KV write can never overflow), "sampling": tuple,
    "eos": int (-1 = none), "key": uint32 [2]}.
    """
    k_req = int(payload.get("decode_steps") or 0)
    if k_req <= 0:
        return None
    if budget < 1:
        raise BufferError(f"KV overflow: no budget for a decode step ({budget})")
    s = payload.get("sampling") or {}
    sampling = (
        float(s.get("temperature", 0.0)),
        int(s.get("top_k", 0)),
        float(s.get("top_p", 1.0)),
        float(s.get("min_p", 0.0)),
    )
    key = payload.get("key")
    if key is None:
        key = jax.random.PRNGKey(int(payload.get("seed", 0) or 0))
    eos = payload.get("eos")
    return {
        "k": max(1, min(k_req, int(budget))),
        "sampling": sampling,
        "eos": -1 if eos is None else int(eos),
        "key": np.asarray(key, np.uint32),
    }


def cache_intact(cache) -> bool:
    """Whether the shared KV cache survived a raising dispatch. The
    decode jits DONATE the cache: a failure raised before dispatch (host
    -side — admission, shape, a bug in array build) leaves the buffers
    untouched and per-dispatch isolation holds, but a device-side
    failure after donation leaves the executor's cache reference
    pointing at deleted buffers — every later dispatch would die on it,
    so the window must stop dispatching and fail the REMAINING entries
    (already-committed results stay committed) with a clear error."""
    k = getattr(cache, "k", None)
    return not (hasattr(k, "is_deleted") and k.is_deleted())


def kstep_hi(start: int, n: int, k: int) -> int:
    """Ring high-water frontier after a K-step window: `n` committed
    writes plus ONE frozen-frontier garbage slot when eos deactivated the
    lane early — a frozen row rewrites the SAME frontier slot each tail
    step (models/qwen3.decode_k semantics), it does not advance, so the
    mark must not claim the full K. Overstating it makes the
    `hi - start_pos > RING_MARGIN` replay guard reject legitimate
    rollbacks after an early stop."""
    return start + min(n + 1, k)


def fuse_kstep_group(decode_k_fn, params, cache, lens, lanes: int, grp,
                     ads=None):
    """Run one sampling-group of co-batched K-step lanes as ONE fused scan
    — the shared core of BatchedExecutor._run_decode_batch and
    BatchedStageExecutor.process_batch, so the group invariants (group K =
    the MINIMUM budget-clamped request; one boundary sync of K tokens per
    dispatch) have exactly one definition.

    decode_k_fn: a jit with the _decode_k_serve signature
    (params, cache, toks, lengths, active, keys, eos, k, t, tk, tp, mp,
    ads=None) -> (cache, seq, n_new, keys'). grp: [(lane, token, ks)]
    where every parse_kstep dict shares one sampling tuple. `ads`: the
    multi-tenant LoRA pools + per-lane slot ids (ops/lora pool contract)
    — every fused step serves each lane its own adapter. Returns
    (kg, seq [kg, L], n_new [L], nkeys [L, 2], new_cache) with the three
    arrays already materialized on the host.
    """
    kg = min(ks["k"] for _lane, _tok, ks in grp)
    toks = np.zeros((lanes,), np.int32)
    active = np.zeros((lanes,), bool)
    eos = np.full((lanes,), -1, np.int32)
    keys = np.zeros((lanes, 2), np.uint32)
    sampling = None
    for lane, token, ks in grp:
        toks[lane] = token
        active[lane] = True
        eos[lane] = ks["eos"]
        keys[lane] = ks["key"]
        sampling = ks["sampling"]
    t, tk, tp, mp = sampling
    cache, seq, n_new, nkeys = decode_k_fn(
        params, cache, jnp.asarray(toks), jnp.asarray(lens, jnp.int32),
        jnp.asarray(active), jnp.asarray(keys), jnp.asarray(eos),
        kg, t, tk, tp, mp, ads=ads,
    )
    # ONE boundary transfer per fused K-step dispatch (the core/batch
    # generate_all pattern); every host read downstream comes off these
    # three materialized arrays
    seq = np.asarray(seq)  # single per-dispatch boundary sync of K tokens for every lane
    n_new = np.asarray(n_new)  # same single boundary sync
    nkeys = np.asarray(nkeys)  # same single boundary sync
    return kg, seq, n_new, nkeys, cache


class Qwen3StageExecutor:
    """Executes one pipeline stage of a Qwen3-family model."""

    def __init__(
        self,
        cfg: ModelConfig,
        spec: StageSpec,
        stage_params: Dict[str, Any],
        max_len: int = 4096,
        max_sessions: int = 64,
        session_ttl_s: float = 600.0,
        initial_kv_len: int = 256,
    ):
        self.cfg = cfg
        self.spec = spec
        self.params = stage_params
        self.max_len = max_len
        self.initial_kv_len = initial_kv_len
        self.sessions = SessionStore(max_sessions, session_ttl_s)
        # ring-KV replay safety: high-water mark of positions ever written
        # per session. A replay rollback is safe only while hi - start_pos
        # stays under RING_MARGIN (the aliasing invariant); guarding on the
        # CURRENT length alone would let compound replays walk the frontier
        # back past data the rings have already overwritten. Own lock: the
        # per-session locks don't cover cross-session mutations (prune).
        self._ring_hi: Dict[str, int] = {}
        self._hi_lock = threading.Lock()

        cfg_ = cfg
        spec_ = spec

        # cache donation: the KV update writes in place on device instead of
        # XLA copying the whole per-session buffer every step (the engines
        # already do this; the caller always rebinds to the returned cache).
        # If a dispatch fails mid-flight the donated-but-stale store entry
        # surfaces as a deleted-array error on the session's NEXT chunk ->
        # 500 -> the client restarts the session (retryable by design).
        @partial(jax.jit, donate_argnames=("cache",))
        def _run(params, x, start_pos, cache: KVCache, real_len):
            # x: tokens [B, S] on the first stage, hidden [B, S, H] otherwise
            if spec_.is_first:
                hidden = qwen3.embed(params, x, cfg_)
            else:
                hidden = x
            s = hidden.shape[1]
            positions = start_pos + jnp.broadcast_to(jnp.arange(s), hidden.shape[:2])
            hidden, nc = qwen3.forward_layers_cached(
                params["layers"], cfg_, hidden, positions, cache, cache.length,
                real_end=cache.length + real_len,
                layer_offset=spec_.start_layer,
            )
            new_cache = dataclasses.replace(nc, length=cache.length + real_len)
            if spec_.is_last:
                # client-side sampling: ship float32 logits of the LAST real
                # token only (reference ships full hidden states every hop)
                last = hidden[jnp.arange(hidden.shape[0]), real_len - 1]
                logits = qwen3.unembed(params, cfg_, last[:, None, :])[:, 0]
                return {"logits": logits}, new_cache
            return {"hidden": hidden}, new_cache

        self._run = _run

        # multi-step fused decode (single-stage topologies only: the K-step
        # inner loop needs the whole model — a pipeline stage's next token
        # depends on every other stage, so multi-stage swarms keep the
        # per-token relay and amortize dispatch via stage co-batching
        # instead). Sampling runs ON DEVICE (models/qwen3.decode_k), so the
        # host syncs once per K tokens instead of shipping logits per token.
        self._decode_k = None
        if spec.is_first and spec.is_last:

            @partial(
                jax.jit, donate_argnames=("cache",),
                static_argnames=("k", "temperature", "top_k", "top_p",
                                 "min_p"),
            )
            def _decode_k(params, tok, cache: KVCache, key, eos, k: int,
                          temperature: float, top_k: int, top_p: float,
                          min_p: float):
                lengths = jnp.broadcast_to(cache.length, (1,))
                nc, seq, n_new, keys, _lps, _tis, _tls = qwen3.decode_k(
                    params, cfg_, tok, cache, lengths,
                    jnp.ones((1,), bool), key[None], k,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    min_p=min_p, eos=eos,
                )
                nc = dataclasses.replace(nc, length=cache.length + n_new[0])
                return seq[:, 0], n_new[0], keys[0], nc

            self._decode_k = _decode_k

    # -- session cache management ------------------------------------------

    def _cache_for(self, session_id: str, real_len: int, padded_len: int) -> KVCache:
        """Cache with room for the PADDED chunk write (the jitted update
        writes padded_len rows; sizing by real_len alone would let
        dynamic_update_slice clamp and silently overwrite the newest real
        slots). The real-token budget is still capped at max_len."""
        needed = max(real_len, padded_len)
        cache = self.sessions.get(session_id)
        if cache is None:
            # a NEW incarnation (first chunk, or the id was evicted): any
            # leftover high-water mark belongs to the old rings and would
            # wrongly reject this session's legal replays
            with self._hi_lock:
                self._ring_hi.pop(session_id, None)
            cache = KVCache.create(
                self.cfg,
                self.spec.num_layers,
                1,
                max(self.initial_kv_len, bucket_len(needed)),
                layer_offset=self.spec.start_layer,
            )
        if int(cache.length) + real_len > self.max_len:
            raise BufferError(
                f"session {session_id}: KV overflow ({int(cache.length)}+{real_len} > {self.max_len})"
            )
        if int(cache.length) + needed > cache.max_len:
            cache = grow(cache, bucket_len(int(cache.length) + needed))
        return cache

    def _rollback_for(
        self, session_id: str, cache: KVCache, start_pos: int
    ) -> KVCache:
        """Resolve a chunk whose start_pos is not the session frontier: a
        chunk STARTING BEFORE the frontier is a deterministic REPLAY (the
        client re-sent after a lost response — e.g. an entry died
        mid-answer and its handed-off KV already holds the chunk): roll
        back to the chunk start and recompute. The rewritten KV is
        identical (deterministic forward); ring buffers stay exact while
        the rollback depth is under the ring margin (core.cache aliasing
        invariant). Call under the session lock."""
        cur = int(cache.length)
        if start_pos == cur:
            return cache
        if not 0 <= start_pos < cur:
            raise ValueError(
                f"session {session_id}: start_pos {start_pos} != cache "
                f"length {cur} (out-of-order chunk)"
            )
        with self._hi_lock:
            hi = max(self._ring_hi.get(session_id, 0), cur)
        if cache.k_loc is not None and hi - start_pos > RING_MARGIN:
            raise ValueError(
                f"session {session_id}: replay rollback to "
                f"{start_pos} exceeds the ring margin (high-water "
                f"mark {hi})"
            )
        return dataclasses.replace(cache, length=jnp.int32(start_pos))

    # -- public API ---------------------------------------------------------

    def process(self, session_id: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Run this stage for one request.

        payload: {"tokens": int32 [B, S]} on stage 0, else {"hidden": [B, S, H]};
        plus "start_pos": int (absolute position of the chunk's first token).
        Padded chunks pass "real_len" (tokens beyond it are bucket padding).
        Returns {"hidden": ...} or, on the last stage, {"logits": [B, V]}.

        A payload carrying "decode_steps" takes the multi-step fused
        decode path instead (single-stage topologies; see
        _process_decode_k).
        """
        # route on the SAME predicate parse_kstep uses (k_req > 0): a
        # zero/negative decode_steps is a legacy single-token step on
        # every executor, not an assertion failure here alone
        if int(payload.get("decode_steps") or 0) > 0:
            return self._process_decode_k(session_id, payload)
        start_pos = int(payload.get("start_pos", 0))
        if self.spec.is_first:
            toks = np.asarray(payload["tokens"], dtype=np.int32)
            real_len = int(payload.get("real_len", toks.shape[1]))
            # pad prompt chunks to a power-of-two bucket (single-token decode
            # steps stay unpadded) so jit compiles once per bucket
            if toks.shape[1] > 1:
                b = bucket_len(toks.shape[1])
                toks = np.pad(toks, [(0, 0), (0, b - toks.shape[1])])
            x = jnp.asarray(toks)
        else:
            h = np.asarray(payload["hidden"])
            real_len = int(payload.get("real_len", h.shape[1]))
            # upstream ships only real rows (wire diet); re-pad to the bucket
            # locally so jit still compiles once per bucket
            if h.shape[1] > 1:
                b = bucket_len(max(h.shape[1], real_len))
                h = np.pad(h, [(0, 0), (0, b - h.shape[1]), (0, 0)])
            x = jnp.asarray(h, dtype=self.cfg.jnp_dtype)

        lock = self.sessions.lock_for(session_id)
        with lock:
            cache = self._cache_for(session_id, real_len, int(x.shape[1]))
            cache = self._rollback_for(session_id, cache, start_pos)
            out, new_cache = self._run(
                self.params, x, jnp.int32(start_pos), cache, jnp.int32(real_len)
            )
            self.sessions.put(session_id, new_cache)
            if new_cache.k_loc is not None:
                with self._hi_lock:
                    self._ring_hi[session_id] = max(
                        self._ring_hi.get(session_id, 0), start_pos + real_len
                    )
                    if len(self._ring_hi) > 2 * self.sessions.max_sessions:
                        # opportunistic prune: drop marks for evicted sessions
                        live = set(self.sessions.ids())
                        self._ring_hi = {
                            s: h for s, h in self._ring_hi.items() if s in live
                        }

        result = {k: np.asarray(v) for k, v in out.items()}
        if "hidden" in result:
            # ship only the real rows: a 17-token chunk must not ride the
            # wire as 32 rows of [B, S, H] bucket padding (VERDICT r1 #8)
            result["hidden"] = result["hidden"][:, :real_len]
        # relay metadata: downstream stages need the chunk's absolute
        # position and real (unpadded) length
        result["real_len"] = real_len
        result["start_pos"] = start_pos
        return result

    def _process_decode_k(
        self, session_id: str, payload: Dict[str, Any]
    ) -> Dict[str, Any]:
        """Multi-step fused decode for a solo session: K decode steps +
        on-device sampling in ONE dispatch (models/qwen3.decode_k) —
        one host sync per K tokens instead of one logits round trip per
        token.

        payload: {"tokens": [[last_tok]], "start_pos", "decode_steps": K}
        plus the optional parse_kstep keys (sampling/eos/key/seed).
        Returns {"tokens": [[t_0..t_{n-1}]], "real_len": n (tokens
        actually committed — n < K only when `eos` fired mid-window),
        "decode_steps": the K actually run (clamped at the KV budget),
        "start_pos", "key": the advanced PRNG chain}.

        The session frontier advances by exactly n, and the replay-
        rollback protocol is untouched: a re-sent chunk starting before
        the frontier rolls back and recomputes deterministically
        (greedy, or sampled with the same key).
        """
        if self._decode_k is None:
            raise ValueError(
                "decode_steps requires a single-stage (whole-model) "
                "topology — pipeline stages relay per token"
            )
        toks = np.asarray(payload["tokens"], dtype=np.int32)
        if toks.shape != (1, 1):
            raise ValueError(
                f"multi-step decode expects tokens [1, 1], got {toks.shape}"
            )
        start_pos = int(payload.get("start_pos", 0))
        if start_pos <= 0:
            raise ValueError(
                "multi-step decode needs an established frontier "
                "(start_pos > 0)"
            )
        ks = parse_kstep(payload, self.max_len - start_pos)
        assert ks is not None
        k_eff = ks["k"]
        lock = self.sessions.lock_for(session_id)
        with lock:
            cache = self._cache_for(session_id, 1, 1)
            cache = self._rollback_for(session_id, cache, start_pos)
            if start_pos + k_eff > cache.max_len:
                cache = grow(cache, bucket_len(start_pos + k_eff))
            t, tk, tp, mp = ks["sampling"]
            seq, n_new, nkey, new_cache = self._decode_k(
                self.params, jnp.asarray(toks[0]), cache,
                jnp.asarray(ks["key"]), jnp.int32(ks["eos"]), k_eff,
                t, tk, tp, mp,
            )
            seq = np.asarray(seq)
            n = int(n_new)
            self.sessions.put(session_id, new_cache)
            if new_cache.k_loc is not None:
                with self._hi_lock:
                    self._ring_hi[session_id] = max(
                        self._ring_hi.get(session_id, 0),
                        kstep_hi(start_pos, n, k_eff),
                    )
        return {
            "tokens": [seq[:n].tolist()],
            "real_len": n,
            "decode_steps": k_eff,
            "start_pos": start_pos,
            "key": np.asarray(nkey).tolist(),
        }

    def end_session(self, session_id: str) -> None:
        self.sessions.drop(session_id)
        with self._hi_lock:
            self._ring_hi.pop(session_id, None)

    def export_sessions(self, only: "str | None" = None):
        """Snapshot every live session's KV as host arrays for migration
        handoff: [(sid, {"k", "v", "length"[, "kv_dtype"][, "k_loc",
        "v_loc"]})]. Global-layer slots past `length` are garbage and not
        shipped (slice to the populated prefix); sliding-layer RINGS ship
        whole (every slot may be live — they're O(window) anyway). Narrow
        float dtypes the wire codec doesn't carry (fp8 KV) ship as a
        same-shape uint8 byte view plus their dtype name. `only` exports a
        single session (the deliberate prefill->decode handoff path)."""
        from inferd_tpu.runtime import handoff

        out = []
        for sid, cache in self.sessions.items_snapshot():
            if only is not None and sid != only:
                continue
            with self.sessions.lock_for(sid):
                cur = self.sessions.get(sid)
                if cur is None:
                    continue
                n = int(cur.length)
                if n == 0:
                    continue
                hi = None
                kl = vl = None
                if cur.k_loc is not None:
                    kl, vl = np.asarray(cur.k_loc), np.asarray(cur.v_loc)
                    with self._hi_lock:
                        # the rings' stale slots reach the HIGH-WATER mark,
                        # which a replay rollback can leave above `length` —
                        # the importer's replay guard needs the true value
                        hi = max(self._ring_hi.get(sid, 0), n)
                out.append((sid, handoff.encode(
                    np.asarray(cur.k[:, :, :n]), np.asarray(cur.v[:, :, :n]),
                    n, kl, vl, hi,
                )))
        return out

    def session_lengths(self) -> Dict[str, int]:
        """{session_id: committed KV length} — the cheap frontier surface
        the standby replicator polls (runtime/repl.SessionReplicator)."""
        out = {}
        for sid, cache in self.sessions.items_snapshot():
            n = int(cache.length)
            if n > 0:
                out[sid] = n
        return out

    def export_session_delta(self, session_id: str, since: int):
        """Incremental flavor of export_sessions for standby replication:
        the handoff-schema payload covering positions [since, length)
        plus a "start" key, or None when the session is unknown or holds
        nothing new. Sliding-layer rings ship WHOLE with every delta
        (every slot may be live and they're O(window)); global layers
        ship only the new slots. since == 0 degenerates to the full
        export_sessions payload + start."""
        from inferd_tpu.runtime import handoff
        from inferd_tpu.runtime.repl import START_KEY

        with self.sessions.lock_for(session_id):
            cur = self.sessions.get(session_id)
            if cur is None:
                return None
            n = int(cur.length)
            since = max(0, int(since))
            if n <= since:
                return None
            hi = None
            kl = vl = None
            if cur.k_loc is not None:
                kl, vl = np.asarray(cur.k_loc), np.asarray(cur.v_loc)
                with self._hi_lock:
                    hi = max(self._ring_hi.get(session_id, 0), n)
            payload = handoff.encode(
                np.asarray(cur.k[:, :, since:n]),
                np.asarray(cur.v[:, :, since:n]),
                n, kl, vl, hi,
            )
            payload[START_KEY] = since
            return payload

    def import_session(self, session_id: str, payload: Dict[str, Any]) -> bool:
        """Adopt a migrated session's KV (the receiving replica serves the
        same stage, so layer/head shapes must match). Never clobbers an
        existing session of the same id."""
        from inferd_tpu.runtime import handoff

        if payload.get("adapter") is not None:
            # a tenant session's KV was built with its adapter; the solo
            # executor has no registry (--adapters is lane-executor-only)
            # so adopting would silently resume on the base weights —
            # decline and let it land on a registry replica or restart
            return False
        dec = handoff.decode(
            payload, self.cfg, self.spec.num_layers, self.spec.start_layer,
            self.max_len, want_ring=self.cfg.sliding_window > 0,
        )
        if dec is None:
            return False
        k, v, n = dec["k"], dec["v"], dec["n"]
        k_loc, v_loc = dec["k_loc"], dec["v_loc"]
        with self.sessions.lock_for(session_id):
            if self.sessions.get(session_id) is not None:
                return False
            buf = max(self.initial_kv_len, bucket_len(n))
            if buf < k.shape[2]:  # shipped more than the target bucket: trim
                k, v = k[:, :, :buf], v[:, :, :buf]
            elif buf > k.shape[2]:
                pad = [(0, 0), (0, 0), (0, buf - k.shape[2]), (0, 0), (0, 0)]
                k = np.pad(k, pad)
                v = np.pad(v, pad)
            cache = KVCache(
                k=jnp.asarray(k, self.cfg.kv_jnp_dtype),
                v=jnp.asarray(v, self.cfg.kv_jnp_dtype),
                length=jnp.int32(n),
                k_loc=None if k_loc is None else jnp.asarray(k_loc, self.cfg.kv_jnp_dtype),
                v_loc=None if v_loc is None else jnp.asarray(v_loc, self.cfg.kv_jnp_dtype),
            )
            self.sessions.put(session_id, cache)
            if k_loc is not None:
                with self._hi_lock:
                    self._ring_hi[session_id] = dec["hi"]
        return True

    def fork_session(
        self, new_session_id: str, parent_session_id: str, prefix_len: int
    ) -> bool:
        """Seed a NEW session's KV with the first `prefix_len` slots of an
        existing session's cache — stage-local prefix caching. Distributed
        prefix reuse = every stage of the pipeline forking the same parent
        (the client drives this; inner stages never see tokens, so a
        token-hash cache could only ever work on stage 0).

        Returns False when the parent is unknown here or too short — the
        caller falls back to a full prefill."""
        if prefix_len <= 0:
            return False
        with self.sessions.lock_for(parent_session_id):
            parent = self.sessions.get(parent_session_id)
            if parent is None or int(parent.length) < prefix_len:
                return False
            with self._hi_lock:
                parent_hi = max(
                    self._ring_hi.get(parent_session_id, 0), int(parent.length)
                )
            if (
                parent.k_loc is not None
                and parent_hi - prefix_len > RING_MARGIN
            ):
                # ring KV: the parent's stream ran more than the ring margin
                # past the fork point, so its sliding-layer rings have
                # overwritten slots whose stale data would alias INTO the
                # child's windows (models/qwen3._ring_attend_update
                # invariant). Pinned prefixes never advance, so the prefix-
                # cache path is unaffected; a clean False re-prefills.
                return False
            # slice to the fork's own bucket: a long-running parent must not
            # make every child carry its full buffer
            nb = min(
                max(self.initial_kv_len, bucket_len(prefix_len)), parent.max_len
            )
            if nb == parent.max_len:
                # a full-width slice short-circuits to the SAME array object;
                # the child's first donated step would delete the parent's
                # cache through the shared buffer — force a real copy
                k, v = jnp.copy(parent.k), jnp.copy(parent.v)
            else:
                k, v = parent.k[:, :, :nb], parent.v[:, :, :nb]
            child = KVCache(
                k=k, v=v, length=jnp.int32(prefix_len),
                # rings are fixed-size: always a full copy (sharing any leaf
                # with the parent would let the child's donated steps delete
                # the parent's buffers)
                k_loc=None if parent.k_loc is None else jnp.copy(parent.k_loc),
                v_loc=None if parent.v_loc is None else jnp.copy(parent.v_loc),
            )
        self.sessions.put(new_session_id, child)
        if child.k_loc is not None:
            # the child inherits the parent's ring CONTENT, whose stale
            # slots reach up to the parent's high-water mark
            with self._hi_lock:
                self._ring_hi[new_session_id] = max(parent_hi, prefix_len)
        return True


class CounterStageExecutor:
    """Counter-model backend behind the same process() surface (the
    reference's NNForwardTask trick, task.py:24-42, as a first-class
    executor — distribution logic testable with no model weights)."""

    def __init__(self, spec: StageSpec):
        from inferd_tpu.models.counter import CounterStage

        self.spec = spec
        self.model = CounterStage(spec.stage, spec.num_stages)
        self.sessions = SessionStore()

    def process(self, session_id: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        return self.model.forward(payload, session_id)

    def end_session(self, session_id: str) -> None:
        self.sessions.drop(session_id)

    def fork_session(
        self, new_session_id: str, parent_session_id: str, prefix_len: int
    ) -> bool:
        # counter state rides the payload, not the session — nothing to copy
        return True


def make_executor(
    cfg: ModelConfig,
    spec: StageSpec,
    stage_params: Optional[Dict[str, Any]] = None,
    backend: str = "qwen3",
    **kw,
):
    if backend == "counter":
        return CounterStageExecutor(spec)
    assert stage_params is not None, "qwen3 backend needs stage params"
    return Qwen3StageExecutor(cfg, spec, stage_params, **kw)
