"""Swarm node: hosts one pipeline stage, relays activations, rebalances.

Capability parity with /root/reference/petals/node.py:14-158 (aiohttp server
with /nn_forward + /reassign, relay to the next stage's best node, periodic
rebalance loop) and node_info.py / task_scheduler.py, redesigned:

  * stage compute runs in a worker thread pool — the event loop keeps
    serving network I/O during a forward (reference ran torch synchronously
    inside the async handler, SURVEY B5);
  * load metric = actual in-flight requests, announced to the swarm store on
    every change (reference: task_scheduler.py:16-36);
  * stage migration WORKS: /reassign (and the balancer) loads the target
    stage's checkpoint from the shared parts store, swaps the executor, and
    re-announces (the reference's set_stage was a no-op and its weight path
    was wrong — SURVEY B1/B2);
  * wire format is the safe msgpack tensor codec (runtime/wire.py), not
    base64 JSON or pickle.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import math
import os
import threading
import time
import uuid
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional, Tuple

import aiohttp
import numpy as np
from aiohttp import ClientSession, ClientTimeout, web

from inferd_tpu.config import ModelConfig
from inferd_tpu.control.balance import Balancer
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.control.path_finder import NoNodeForStage, PathFinder, node_addr
from inferd_tpu.obs import canary as canarylib
from inferd_tpu.obs import devtel as devtellib
from inferd_tpu.obs import events as eventslib
from inferd_tpu.obs import export as obs_export
from inferd_tpu.obs import health as healthlib
from inferd_tpu.obs import prof as proflib
from inferd_tpu.obs import trace as tracelib
from inferd_tpu.obs import tsdb as tsdblib
from inferd_tpu.parallel import stages as stagelib
from inferd_tpu.parallel.mesh import MeshPlan
from inferd_tpu.runtime import repl as repllib
from inferd_tpu.runtime import wire
from inferd_tpu.runtime.executor import make_executor
from inferd_tpu.runtime.window import WindowedBatcher
from inferd_tpu.utils import lockwatch
from inferd_tpu.utils import retry as retrylib
from inferd_tpu.utils.chaos import Chaos, ChaosDrop
from inferd_tpu.utils.metrics import Metrics
from inferd_tpu.utils.profiling import Profiler

log = logging.getLogger(__name__)


def _warmup_executor(executor, journal=None) -> None:
    """Best-effort eager compile of a freshly loaded executor's decode-step
    jit: one single-token forward through a throwaway session, so the first
    REAL request after a stage migration doesn't pay XLA compile latency
    (and so reshard.ms_to_serving measures the full reassign ->
    ready-to-serve interval, compile included). Works for every executor
    type via the shared process() contract; non-first stages feed a dummy
    hidden row. Failures are swallowed — warmup must never block serving
    (the first real request just compiles lazily, the pre-migration
    behavior) — but PROMOTED to a journal event + `events.
    executor.warmup_failed` counter: a silently failed warmup is exactly
    when a migrated node starts eating first-request compile storms, and
    a debug log line is invisible then (the counter doubles as a free SLO
    rule input — obs.health DEFAULT_RULES)."""
    sid = "__warmup__"
    t0 = time.perf_counter()
    try:
        spec = getattr(executor, "spec", None)
        cfg = getattr(executor, "cfg", None)
        if spec is not None and not spec.is_first:
            payload = {
                "hidden": np.zeros((1, 1, cfg.hidden_size), np.float32),
                "start_pos": 0, "real_len": 1,
            }
        else:
            payload = {"tokens": [[1]], "start_pos": 0, "real_len": 1}
        executor.process(sid, payload)
        if hasattr(executor, "process_batch"):
            # stage-batch executors serve decode through a SEPARATE
            # co-batched jit — compile it too (it is the serving hot path)
            step = dict(payload, start_pos=1)
            executor.process(sid, step)
        if journal is not None:
            journal.emit(
                "executor.warmup_ok",
                ms=round((time.perf_counter() - t0) * 1e3, 1),
            )
    except Exception as e:
        log.warning(
            "executor warmup failed (first request will compile): %s", e,
            exc_info=True,
        )
        if journal is not None:
            journal.emit(
                "executor.warmup_failed",
                error=f"{type(e).__name__}: {e}"[:200],
                ms=round((time.perf_counter() - t0) * 1e3, 1),
            )
    finally:
        try:
            executor.end_session(sid)
        except Exception:
            pass


# canonical home moved next to the gossip record schema (control.dht);
# re-exported here for the existing runtime/tests import surface
from inferd_tpu.control.dht import sess_hash  # noqa: E402,F401

class _ClientGone(Exception):
    """The streaming client disconnected mid-write: abort the stream
    quietly (no restart re-run for a dead socket)."""


def _is_decode_step(payload) -> bool:
    """True when the /forward payload is a single-token decode step at an
    established frontier — the only shape the stage window co-batches
    (prefill chunks and new sessions keep the per-session path)."""
    if not isinstance(payload, dict):
        return False
    try:
        if int(payload.get("start_pos", 0)) <= 0:
            return False
        x = payload.get("tokens")
        if x is None:
            x = payload.get("hidden")
        n = payload.get("real_len")
        if n is None:
            n = np.shape(x)[1]
        return int(n) == 1
    except Exception:
        return False  # malformed payloads fail in the guarded compute


#: Buckets for the /generate user-SLI histograms: the SAME whole-chain
#: ladder the canary probes use (obs.canary), so probe and user latency
#: compare bucket for bucket.
_GENERATE_BOUNDS_MS = canarylib.CHAIN_BOUNDS_MS

FORWARD_PATH = "/forward"
REASSIGN_PATH = "/reassign"
END_SESSION_PATH = "/end_session"
FORK_SESSION_PATH = "/fork_session"
GENERATE_PATH = "/generate"
IMPORT_SESSION_PATH = "/import_session"
EXPORT_SESSION_PATH = "/export_session"
DRAIN_PATH = "/drain"
REPLICATE_SESSION_PATH = "/replicate_session"


@dataclasses.dataclass
class NodeInfo:
    """Node identity + placement (reference node_info.py:1-28, with a
    set_stage that actually updates state — fixing B1)."""

    name: str
    host: str
    port: int
    stage: int
    num_stages: int
    capacity: int = 4
    model_name: str = ""

    @property
    def node_id(self) -> str:
        return f"{self.host}:{self.port}"

    def set_stage(self, stage: int) -> None:
        self.stage = stage


class TaskScheduler:
    """Runs stage compute off the event loop; load = in-flight count."""

    def __init__(self, on_load_change, workers: int = 2):
        self.inflight = 0
        self._on_load_change = on_load_change
        self._pool = ThreadPoolExecutor(max_workers=workers, thread_name_prefix="stage")
        self._lock = asyncio.Lock()

    async def run(self, fn, *args):
        loop = asyncio.get_running_loop()
        async with self._lock:
            self.inflight += 1
            self._on_load_change()
        try:
            return await loop.run_in_executor(self._pool, fn, *args)
        finally:
            async with self._lock:
                self.inflight -= 1
                self._on_load_change()

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


class Node:
    """One swarm node process."""

    def __init__(
        self,
        info: NodeInfo,
        cfg: ModelConfig,
        parts_dir: str,
        dht: SwarmDHT,
        backend: str = "qwen3",
        max_len: int = 4096,
        rebalance_period_s: float = 10.0,
        hop_timeout_s: float = 120.0,
        max_sessions: int = 64,
        chaos: Optional[Chaos] = None,
        enable_profiling: bool = False,
        mesh_plan: Optional[MeshPlan] = None,
        mesh_slots: int = 8,
        quant: str = "none",
        batch_lanes: int = 0,
        stage_lanes: int = 0,
        paged_block_size: int = 0,
        kv_blocks: int = 0,
        prefill_chunk: int = 0,
        window_ms: float = 2.0,
        spec_draft_layers: int = 0,
        spec_k: int = 4,
        lora: Optional[str] = None,
        adapters: Optional[str] = None,
        adapter_slots: int = 0,
        trace_dir: Optional[str] = None,
        canary_interval_s: float = 0.0,
        prof_interval_s: float = 0.0,
        prof_priors: Optional[str] = None,
        hedge_delay_ms: float = 0.0,
        hedge_mode: str = "advertised",
        admission_reserve: float = 0.05,
        standby_repl: bool = False,
        repl_interval_s: float = 0.5,
        rescue_bounces: int = 6,
    ):
        self.info = info
        self.cfg = cfg
        self.parts_dir = parts_dir
        self.dht = dht
        self.backend = backend
        self.max_len = max_len
        self.hop_timeout_s = hop_timeout_s
        self.max_sessions = max_sessions
        self.metrics = Metrics()
        # swarm-wide request tracing (obs.trace): spans recorded host-side
        # into this ring, periodically appended to
        # <trace_dir>/<node_id>.spans.jsonl when --trace-dir is set (the
        # merge CLI's per-node input), always served live at /spans
        self.tracer = tracelib.SpanRecorder(service=info.node_id)
        # fleet flight recorder (obs.events): typed events (migrations,
        # rescues, dead peers, lane evictions, compiles, ...) with the
        # active trace_id attached; flushed next to the span file as
        # <trace_dir>/<node_id>.events.jsonl, served live at /events, and
        # mirrored into `events.*` counters for /metrics + SLO rules
        self.journal = eventslib.EventJournal(
            service=info.node_id, metrics=self.metrics
        )
        # late-bind the lock sanitizer's inversion journal (process-
        # global: multi-node tests share one watcher, last node wins —
        # inversions are process properties, not per-node ones). The
        # emit rides the journal's own INFERD_EVENTS gate.
        lockwatch.set_journal(self.journal.emit)
        # XLA compile detector (obs.devtel): wraps the executor's jitted
        # fns; each cache-size growth becomes compile.begin/end events, a
        # compile.events counter, and a compile.ms histogram sample
        self.compile_watch = devtellib.CompileWatch(self.metrics, self.journal)
        self.trace_dir = trace_dir
        # windowed telemetry plane (obs.tsdb): bounded rings of per-window
        # deltas over this registry, sampled by the 1 s telemetry tick —
        # the trailing-window source behind gossip/health quantiles,
        # GET /metrics/history, burn-rate SLO rules, and fleet SLIs
        self.tsdb = tsdblib.Tsdb(
            self.metrics, service=info.node_id,
            meta={"stage": info.stage, "num_stages": info.num_stages},
        )
        self.tsdb_period_s = 1.0
        # trailing horizon for the gossiped/windowed quantiles — "the
        # last minute" by default; tests shrink it to fast-forward aging
        self.window_s = tsdblib.TRAILING_WINDOW_S
        # synthetic canary prober (obs.canary): off unless run_node
        # --canary-interval > 0; probes the swarm's entry replicas at a
        # bounded rate, recording ONLY canary.* series
        self.canary_interval_s = canary_interval_s
        self.canary: Optional[canarylib.CanaryProber] = None
        # continuous profiling plane (obs.prof): off unless run_node
        # --prof-interval > 0; a low-duty-cycle tick scans ONE anatomy
        # phase against the live executor's weights when the device is
        # quiet, publishes anatomy.*/roofline.* gauges, and runs the
        # perf-regression sentinel against the committed priors file
        self.prof_interval_s = prof_interval_s
        self.prof_priors = prof_priors
        self.prof: Optional[proflib.LiveAnatomy] = None
        self._prof_task: Optional[asyncio.Task] = None
        # capture lock shared by the manual /profile window and the
        # live-anatomy tick: held for a whole capture so tick micro-scans
        # never pollute the device timeline (and vice versa)
        self._capture_lock = lockwatch.make_lock("capture")
        self._capture_task: Optional[asyncio.Task] = None
        # event-loop stall watchdog (J009's dynamic twin) — started by
        # start() when lockwatch + events are on, journals `loop.stall`
        self._stall_detector: Optional[lockwatch.LoopStallDetector] = None
        # replica-outlier self-detection result ({"value","median","mad",
        # "field"} while this node's trailing p99 diverges from its stage
        # peers) — journaled, gossiped as `outlier`, penalized by routing
        self._outlier_info: Optional[Dict[str, Any]] = None
        self._tsdb_task: Optional[asyncio.Task] = None
        self._windowed_cache: Tuple[float, Optional[Dict[str, float]]] = (0.0, None)
        # SLO verdict + obs gossip fields, cached ~1 s (announce() runs
        # per load change and /health may be polled aggressively)
        self._health_cache: Tuple[float, Optional[Dict[str, Any]]] = (0.0, None)
        self.chaos = chaos
        self.enable_profiling = enable_profiling
        # ---- overload-containment plane (docs/SERVING.md) ----
        # graceful drain: POST /drain flips this; new admissions shed 503
        # code "draining", gossip carries a `draining` flag both routers
        # treat as an exclusion, residents finish or hand off
        self._draining = False
        # pool-aware admission: shed NEW sessions when the paged-KV block
        # pool's free count falls below this fraction of the pool
        # (ROADMAP 2d: backpressure on blocks_free, not lane count)
        self.admission_reserve = admission_reserve
        # hedged relays: after an adaptive (trailing hop p95) delay, an
        # idempotent decode-step relay fires a second copy at another
        # replica and takes the first success. hedge_delay_ms > 0 pins
        # the delay (tests); "advertised" hedges only at replicas that
        # advertise the session's KV, "any" at the second-best ranked
        # pick (stateless backends), "off" disables. The ratio budget
        # caps hedges at <= 5% extra load however slow the tail gets.
        self.hedge_delay_ms = hedge_delay_ms
        self.hedge_mode = hedge_mode
        self.hedge_budget = retrylib.RatioBudget(ratio=0.05, burst=2)
        # the node-side retry budget: the rescue loop's blind re-relays
        # draw from this bucket (same abstraction as the client bucket),
        # so a dead stage produces a bounded rescue rate, not a storm
        self.retry_budget = retrylib.RetryBudget(rate_per_s=4.0, burst=16)
        # dead-peer cooldown (outlier-ejection-lite): a replica whose
        # relay just failed at transport level or answered 5xx is
        # avoided by the FRESH-pick step of _pick_next for this many
        # seconds — new sessions steer around a stalling/dropping
        # replica instead of rediscovering it per request. Never an
        # exclusion for affinity/holder/route picks (KV correctness
        # beats steering) and never applied when it would empty a stage.
        self.peer_cooldown_s = 10.0
        self._peer_cooldown: Dict[str, float] = {}
        # ---- crash-tolerant sessions (async standby KV replication) ----
        # OFF by default: with the flag absent the wire, gossip records,
        # and /metrics stay byte-identical to a build without the plane
        # (docs/SERVING.md "Failover & durability"). Enabled, a periodic
        # tick ships each resident session's newly completed KV past a
        # per-session frontier to a gossip-chosen same-stage standby
        # (anti-affinity: never this node), and THIS node accumulates
        # peers' deltas host-side in the StandbyStore — promoted into
        # the executor only when a failed-over chunk actually arrives.
        self.standby_repl = bool(standby_repl)
        self.repl_interval_s = repl_interval_s
        self.standby: Optional[repllib.StandbyStore] = (
            repllib.StandbyStore(max_sessions=max_sessions)
            if self.standby_repl else None
        )
        self.replicator: Optional[repllib.SessionReplicator] = (
            repllib.SessionReplicator(self._repl_candidates)
            if self.standby_repl else None
        )
        self._repl_task: Optional[asyncio.Task] = None
        # standby peers that recently declined/failed a replication ship:
        # skipped by the standby pick for peer_cooldown_s so a dead or
        # repl-disabled peer isn't re-shipped every tick
        self._repl_peer_cooldown: Dict[str, float] = {}
        # rescue give-up cap: how many times a mid-session chunk landing
        # without its KV bounces through gossip-advertised holders before
        # degrading to the client's 409/restart path (--rescue-bounces;
        # the end_session twin below stays intentionally fixed at ONE
        # bounce — freeing KV early is pure best-effort housekeeping)
        self.rescue_bounces = max(1, int(rescue_bounces))
        self.mesh_plan = mesh_plan
        self.mesh_slots = mesh_slots
        self.quant = quant
        self.batch_lanes = batch_lanes
        # stage-level continuous batching: co-arriving /forward decode
        # steps of concurrent sessions run as ONE device step per window
        # (runtime/stage_batch + runtime/window), and co-batched entries
        # sharing a next hop relay as ONE coalesced envelope (wire.multi)
        self.stage_lanes = stage_lanes
        # paged KV (core.cache.BlockPool): block-granular allocation +
        # refcounted shared-prefix caching with copy-on-write on the lane
        # executors (--paged-kv BLOCK_SIZE; 0 = dense lane slab)
        self.paged_block_size = paged_block_size
        self.kv_blocks = kv_blocks
        # server-side chunked prefill: long admissions ingest in chunks
        # with the device lock released between them, so co-batched decode
        # windows interleave (--prefill-chunk TOKENS; 0 = whole-prompt)
        self.prefill_chunk = prefill_chunk
        self.window_ms = window_ms
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.spec_draft_layers = spec_draft_layers
        self.spec_k = spec_k
        self.lora = lora
        self._lora_adapter = None  # parsed once on first executor load
        # multi-tenant LoRA registry (run_node --adapters; runtime/
        # adapters.AdapterRegistry): catalog of adapter dirs, bounded
        # device-resident slots, per-session binding via the `adapter`
        # envelope key. STRICTLY exclusive with the merged --lora path —
        # merged weights plus per-lane deltas would serve every tenant
        # two adapters (ops.lora.check_exclusive_modes, loud by contract)
        from inferd_tpu.ops import lora as loralib

        loralib.check_exclusive_modes(lora, adapters, owner=info.node_id)
        self.adapters_spec = adapters
        self.adapter_slots = adapter_slots
        self.adapter_registry = None  # built with the executor
        # lazy self-drafting speculative engines for /generate, one per
        # distinct SAMPLING CONFIG (the warp parameters are baked into each
        # engine's jits — greedy requests share one engine, every sampled
        # config gets its own; caches are per-call so engines only cost
        # compile time). Small LRU: an adversarial client cycling
        # temperatures must not accumulate unbounded jit caches.
        # False value = that config's build/run failed (fast path off);
        # _spec_unsupported = structurally impossible on this executor.
        self._spec_engines: "OrderedDict[tuple, Any]" = OrderedDict()
        self._spec_engines_max = 4
        self._spec_unsupported = False
        self._spec_lock = asyncio.Lock()  # one spec run at a time: the
        # opportunistic shed keeps concurrent requests on the batchable loop
        # static top-N width every spec engine/runner compiles with
        # (core.spec_batch.SPEC_TOP_N — one definition; requests asking
        # for more alternatives take the regular loop instead)
        from inferd_tpu.core.spec_batch import SPEC_TOP_N

        self._spec_top_n = SPEC_TOP_N
        self.profiler = Profiler(device_lock=self._capture_lock)
        if mesh_plan is not None and batch_lanes > 0:
            raise ValueError(
                "--mesh and --batch-lanes are mutually exclusive executor "
                "modes (in-mesh pipelined vs single-device continuous "
                "batching) — pick one"
            )
        if stage_lanes > 0 and (mesh_plan is not None or batch_lanes > 0):
            raise ValueError(
                "--stage-lanes (stage-level continuous batching) is "
                "mutually exclusive with --mesh and --batch-lanes"
            )
        if stage_lanes > 0 and backend != "qwen3":
            raise ValueError("--stage-lanes needs the qwen3 backend")
        if paged_block_size > 0 and not (batch_lanes > 0 or stage_lanes > 0):
            raise ValueError(
                "--paged-kv runs on the lane executors — pair it with "
                "--batch-lanes or --stage-lanes"
            )
        if adapters and not (batch_lanes > 0 or stage_lanes > 0):
            raise ValueError(
                "--adapters (multi-tenant batched LoRA) runs on the lane "
                "executors — pair it with --batch-lanes or --stage-lanes"
            )
        if adapters and backend != "qwen3":
            raise ValueError("--adapters needs the qwen3 backend")
        if mesh_plan is not None and info.num_stages != 1:
            raise ValueError(
                "--mesh hosts the WHOLE model pipelined over this node's "
                f"chips, so the swarm topology must be single-stage "
                f"(num_stages={info.num_stages})"
            )

        from inferd_tpu import native as _native

        if _native.codec is None:
            log.info(
                "native wire codec unavailable — running the pure-Python "
                "codec (slower serialization on the hop hot path)"
            )

        self.executor = self._load_executor(info.stage)
        # continuous batching coalesces decode steps of CONCURRENT requests:
        # the worker pool must admit at least one thread per lane (plus the
        # flusher's) or the batch window can never fill past the pool size
        lanes = batch_lanes or stage_lanes
        self.scheduler = TaskScheduler(
            self._announce_load,
            workers=max(2, lanes + 1) if lanes else 2,
        )
        self.balancer = Balancer(
            dht,
            info.num_stages,
            get_own_stage=lambda: self.info.stage,
            change_stage=self.change_stage,
            period_s=rebalance_period_s,
            on_event=self.journal.emit,
        )
        self.path_finder = PathFinder(
            dht, info.num_stages, on_empty_stage=self.balancer.adopt_stage
        )

        self._http: Optional[ClientSession] = None
        self._runner: Optional[web.AppRunner] = None
        self._stopped = asyncio.Event()
        self._sweep_task: Optional[asyncio.Task] = None
        # lazy self-pointed swarm client for /generate (server-driven loop);
        # persistent so its pinned prefix sessions survive across requests
        self._generate_client = None
        self._generate_client_lock = asyncio.Lock()
        # session affinity: (session_id, stage) -> (node_id, ts). A session's
        # KV cache lives on the specific replica that served its earlier
        # chunks — min-load per request would break multi-step generation
        # whenever a stage has >1 replica.
        self._session_next: "OrderedDict[Tuple[str, int], Tuple[str, float]]" = OrderedDict()
        self._session_next_cap = 8192
        # service-time EWMA announced to the swarm (svc_ms): feeds the
        # chain planner's measured-latency edge-cost term on every node
        # (whole-chain routing itself lives in PathFinder.find_best_chain —
        # the reference's designed-but-unwired D*-Lite, wired via
        # _plan_route below)
        self._svc_ewma: Optional[float] = None

    # ------------------------------------------------------------ lifecycle

    def _quantize(self, params, needs_head: bool = True):
        """Apply the node's serving quantization (run_node --quant) to a
        freshly loaded checkpoint. Weight-only int8 halves the per-token
        HBM weight read — the bs=1 decode bottleneck (ops.quant).
        needs_head=False for non-last stages: they hold embed only for the
        token gather and must not allocate a tied-head shadow."""
        from inferd_tpu.ops import quant as quantlib

        return quantlib.apply_quant_mode(
            self.quant, params,
            tie_word_embeddings=self.cfg.tie_word_embeddings,
            needs_head=needs_head,
        )

    def _apply_lora(self, params, spec):
        """Merge the node's LoRA adapter (run_node --lora) into this stage's
        weight slice — BEFORE quantization, so the adapted weights quantize
        and shard exactly like the base checkpoint (ops.lora)."""
        from inferd_tpu.ops import lora as loralib

        # loud, never a silent pass-through: merged weights + the
        # registry's per-lane deltas would serve every tenant TWO
        # adapters (re-checked here because change_stage reloads params
        # long after __init__'s check)
        loralib.check_exclusive_modes(
            self.lora, self.adapters_spec, owner=self.info.node_id
        )
        if not self.lora:
            return params
        if self._lora_adapter is None:
            self._lora_adapter = loralib.load_adapter(self.cfg, self.lora)
            log.info("merged LoRA adapter from %s", self.lora)
        sliced = loralib.slice_adapter(
            self._lora_adapter, spec.start_layer, spec.end_layer + 1,
            owner=f"{self.info.node_id} stage {spec.stage}",
        )
        return loralib.merge_adapter(params, sliced)

    def _build_adapter_registry(self, spec):
        """The stage's adapter registry (run_node --adapters), holding
        each catalog adapter's THIS-STAGE layer slice; journal events
        wire through the node's flight recorder."""
        from inferd_tpu.runtime.adapters import AdapterRegistry

        reg = AdapterRegistry(
            self.cfg, self.adapters_spec, slots=self.adapter_slots,
            start_layer=spec.start_layer, end_layer=spec.end_layer + 1,
            on_event=self._executor_event,
            owner=f"{self.info.node_id} stage {spec.stage}",
        )
        self.adapter_registry = reg
        return reg

    def _load_executor(self, stage: int):
        """Build the stage executor, then wire its observability hooks:
        lane-pool events (lane.evict, ...) flow into the journal, and the
        compile watch wraps its jitted fns so migrations' recompile
        storms become visible compile.begin/end events instead of
        mystery first-request latency."""
        ex = self._build_executor(stage)
        if hasattr(ex, "on_event"):
            ex.on_event = self._executor_event
        self.compile_watch.instrument_executor(ex)
        return ex

    #: Wide eviction-age buckets (ms): prefix entries live seconds (churn
    #: thrash) to hours (cold housekeeping) — the default 10 s ladder
    #: would saturate everything interesting into +Inf.
    _EVICT_AGE_BOUNDS_MS = [
        100, 500, 1000, 5000, 15_000, 60_000, 300_000, 900_000,
        3_600_000, 14_400_000,
    ]

    def _executor_event(self, etype: str, **attrs):
        """Executor flight-recorder hook: journal every event (as before)
        and additionally feed the metrics the journal alone can't carry —
        the prefix-eviction AGE histogram (`kv.prefix_evict_age_ms`): an
        eviction population aging out young means the prefix index is
        thrashing under churn (grow the pool / raise pins), aging out old
        means ordinary LRU housekeeping. Events-gated like every kv.*
        series so a disabled node's /metrics stays byte-identical."""
        if (
            etype == "prefix.evict" and eventslib.enabled()
            and isinstance(attrs.get("age_ms"), (int, float))
        ):
            self.metrics.observe(
                "kv.prefix_evict_age_ms", float(attrs["age_ms"]),
                bounds_ms=self._EVICT_AGE_BOUNDS_MS,
            )
        return self.journal.emit(etype, **attrs)

    def _build_executor(self, stage: int):
        if self.backend == "counter":
            spec = stagelib.StageSpec(stage, self.info.num_stages, stage, stage)
            return make_executor(self.cfg, spec, backend="counter")
        if self.batch_lanes > 0:
            # continuous batching: whole model, sessions map to batch lanes,
            # concurrent decode steps coalesce into one device step
            from inferd_tpu.runtime.batch_executor import BatchedExecutor

            if self.info.num_stages != 1:
                raise ValueError(
                    "--batch-lanes hosts the WHOLE model, so the swarm "
                    f"topology must be single-stage (got {self.info.num_stages})"
                )
            path = stagelib.stage_checkpoint_path(self.parts_dir, 0)
            params, spec, model_name = stagelib.load_stage_checkpoint(path)
            if spec.num_stages != 1:
                raise ValueError(
                    f"--batch-lanes needs a 1-stage checkpoint, got stage "
                    f"{spec.stage}/{spec.num_stages} at {path}"
                )
            self.info.model_name = model_name
            ex = BatchedExecutor(
                self.cfg, self._quantize(self._apply_lora(params, spec)),
                lanes=self.batch_lanes, max_len=self.max_len,
                block_size=self.paged_block_size, kv_blocks=self.kv_blocks,
                prefill_chunk=self.prefill_chunk,
                adapters=(
                    self._build_adapter_registry(spec)
                    if self.adapters_spec else None
                ),
            )
            if self.spec_draft_layers > 0:
                # lane-batched speculation (core.spec_batch): concurrent
                # /generate requests speculate TOGETHER instead of shedding
                # to the regular loop (the solo engine path stays for
                # single-stage stage executors). Capacity note: every
                # lane's budget shrinks by k+1 (verify-chunk headroom).
                try:
                    ex.enable_spec(self.spec_draft_layers, self.spec_k)
                except ValueError as e:
                    log.warning(
                        "lane speculation disabled (%s); serving without", e
                    )
            return ex
        if self.mesh_plan is not None:
            # north-star serving path: whole model in-mesh pipelined over
            # this node's chips (stage checkpoint 0 of a 1-stage manifest
            # holds the full params)
            from inferd_tpu.runtime.mesh_executor import MeshExecutor

            path = stagelib.stage_checkpoint_path(self.parts_dir, 0)
            params, spec, model_name = stagelib.load_stage_checkpoint(path)
            if spec.num_stages != 1:
                raise ValueError(
                    f"mesh mode needs a 1-stage checkpoint, got stage "
                    f"{spec.stage}/{spec.num_stages} at {path}"
                )
            self.info.model_name = model_name
            return MeshExecutor(
                self.cfg, self._quantize(self._apply_lora(params, spec)),
                self.mesh_plan,
                num_slots=self.mesh_slots, max_len=self.max_len,
                # in-mesh speculation: draft layers replicate on every
                # rank, the verify chunk rides the ppermute pipeline —
                # --mesh pp=N nodes can finally speculate (r04 weak #1)
                spec_draft_layers=self.spec_draft_layers,
                spec_k=self.spec_k,
            )
        path = stagelib.stage_checkpoint_path(self.parts_dir, stage)
        params, spec, model_name = stagelib.load_stage_checkpoint(path)
        if spec.stage != stage:
            raise ValueError(f"checkpoint {path} is for stage {spec.stage}, not {stage}")
        self.info.model_name = model_name
        if self.stage_lanes > 0:
            # stage-level continuous batching: sessions map to lanes of ONE
            # shared stage KV cache; co-arriving decode steps run as one
            # device step (the window lives on the node — _attach_window)
            from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

            ex = BatchedStageExecutor(
                self.cfg, spec,
                self._quantize(
                    self._apply_lora(params, spec), needs_head=spec.is_last
                ),
                lanes=self.stage_lanes, max_len=self.max_len,
                session_ttl_s=600.0,
                block_size=self.paged_block_size, kv_blocks=self.kv_blocks,
                prefill_chunk=self.prefill_chunk,
                adapters=(
                    self._build_adapter_registry(spec)
                    if self.adapters_spec else None
                ),
            )
            self._attach_window(ex)
            return ex
        return make_executor(
            self.cfg, spec,
            self._quantize(self._apply_lora(params, spec), needs_head=spec.is_last),
            max_len=self.max_len, max_sessions=self.max_sessions,
        )

    def _attach_window(self, executor) -> None:
        """Give a batch-capable executor its arrival window: co-arriving
        decode steps from different sessions become ONE process_batch
        device step (runtime/window semantics), and the flusher relays the
        co-batch as coalesced envelopes. The window is bound to THIS
        executor instance so a stage migration's swapped-in executor gets
        its own (requests bind the executor at entry, so an in-flight
        window always flushes against the executor it admitted on)."""
        batcher = WindowedBatcher(
            self.window_ms / 1e3,
            lambda entries, _ex=executor: self._run_stage_window(_ex, entries),
            # lock-free live-session count: a solo session must not pay
            # the window latency (and co_possible is called under the
            # batcher's lock — taking the executor's lock here would
            # invert the on_drop -> invalidate lock order)
            co_possible=executor.co_possible,
            # continuous batching: the batch forms at DEVICE-LOCK
            # acquisition (process_batch's drain), not at flusher wake-up,
            # so entries arriving mid-step join the next step instead of
            # fragmenting into a convoy of mini-batches
            swap_in_run=True,
            # gang formation: wait (bounded by window_ms) for every live
            # idle session's step — merges phase-offset session cohorts
            # into one lockstep co-batch (see window.py)
            gang_target=executor.gang_target,
        )
        executor.window = batcher
        batcher.on_event = self.journal.emit
        executor.on_drop = lambda sid: batcher.invalidate(
            lambda payload, _sid=sid: payload[0] == _sid,
            ValueError(f"session {sid} ended mid-request"),
        )

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        await self.dht.start()
        self._http = ClientSession(timeout=ClientTimeout(total=self.hop_timeout_s))
        app = web.Application(client_max_size=1 << 30)
        app.add_routes(
            [
                web.post(FORWARD_PATH, self.handle_forward),
                web.post(REASSIGN_PATH, self.handle_reassign),
                web.post(END_SESSION_PATH, self.handle_end_session),
                web.post(FORK_SESSION_PATH, self.handle_fork_session),
                web.post(GENERATE_PATH, self.handle_generate),
                web.post(IMPORT_SESSION_PATH, self.handle_import_session),
                web.post(EXPORT_SESSION_PATH, self.handle_export_session),
                web.post(DRAIN_PATH, self.handle_drain),
                web.post(REPLICATE_SESSION_PATH, self.handle_replicate_session),
                web.get("/health", self.handle_health),
                web.get("/stats", self.handle_stats),
                web.get("/metrics", self.handle_metrics),
                web.get("/metrics/history", self.handle_metrics_history),
                web.get("/spans", self.handle_spans),
                web.get("/events", self.handle_events),
                web.post("/profile", self.handle_profile),
            ]
        )
        # bounded graceful drain on stop(); crash() drops it to zero
        self._runner = web.AppRunner(app, shutdown_timeout=5.0)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.info.host, self.info.port)
        await site.start()
        self.announce()
        self.balancer.start()
        self.journal.emit(
            "node.start", stage=self.info.stage,
            num_stages=self.info.num_stages,
        )
        self._sweep_task = asyncio.create_task(self._sweep_loop())
        self._tsdb_task = asyncio.create_task(self._tsdb_loop())
        if lockwatch.watching() and eventslib.enabled():
            # stall watchdog: a handler blocking this loop > 50 ms shows
            # up as a `loop.stall` event (env-gated like the lock proxies
            # — INFERD_LOCKWATCH=0 keeps production byte-identical)
            self._stall_detector = lockwatch.LoopStallDetector(
                on_event=self.journal.emit
            ).start()
        if self.standby_repl:
            if not callable(
                getattr(self.executor, "export_session_delta", None)
            ):
                # a loud no-op beats a silent one: the operator asked for
                # crash tolerance, but this executor type (e.g. --mesh)
                # has no incremental export surface yet — this node will
                # ACCEPT peers' shadows and promote them, but its own
                # resident sessions ship nothing and still pay a full
                # restart on a crash
                log.warning(
                    "--standby-repl: executor %s has no "
                    "export_session_delta — this node accepts standby "
                    "shadows but cannot replicate its own sessions "
                    "(crash recovery for residents stays the client-"
                    "restart path)",
                    type(self.executor).__name__,
                )
            self._repl_task = asyncio.create_task(self._repl_loop())
        if self.chaos is not None and getattr(self.chaos, "crash_after", 0):
            # chaos crash_after=N: abrupt handler death — no graceful
            # stop, no handoff, KV lost. The hook schedules crash() (the
            # SIGKILL-equivalent teardown) so failover tests can kill a
            # KV holder deterministically after N forwards
            loop = asyncio.get_running_loop()
            self.chaos.on_crash = lambda: loop.create_task(self.crash())
        if self.canary_interval_s > 0:
            self.canary = canarylib.CanaryProber(
                self._canary_targets, self.metrics, journal=self.journal,
                tracer=self.tracer, interval_s=self.canary_interval_s,
                timeout_s=min(self.hop_timeout_s, 30.0),
            )
            self.canary.start()
        if self.prof_interval_s > 0:
            self._setup_prof()
        if self.spec_draft_layers > 0:
            # compile the greedy speculative engine off the critical path;
            # the first request then hits a warm engine (or waits briefly
            # on the shared build) instead of paying it alone
            self._spec_prebuild_task = asyncio.create_task(
                self._prebuild_spec_engine()
            )
        log.info(
            "node %s up: stage %d/%d on %s:%d",
            self.info.name, self.info.stage, self.info.num_stages,
            self.info.host, self.info.port,
        )

    async def stop(self) -> None:
        self.dht.withdraw()
        if self._stall_detector is not None:
            self._stall_detector.stop()
            self._stall_detector = None
        if self._repl_task:
            self._repl_task.cancel()
            try:
                await self._repl_task
            except asyncio.CancelledError:
                pass
            self._repl_task = None
        if self._sweep_task:
            self._sweep_task.cancel()
            try:
                await self._sweep_task
            except asyncio.CancelledError:
                pass
        if self._tsdb_task:
            self._tsdb_task.cancel()
            try:
                await self._tsdb_task
            except asyncio.CancelledError:
                pass
            self._tsdb_task = None
        if self.canary is not None:
            await self.canary.stop()
            self.canary = None
        for task_attr in ("_prof_task", "_capture_task"):
            task = getattr(self, task_attr, None)
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                setattr(self, task_attr, None)
        if self.profiler.active_dir is not None:
            # a capture window still open at shutdown: close it so the
            # trace flushes (and the capture lock releases)
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, self.profiler.stop
                )
            except Exception:
                log.exception("profiler stop at shutdown failed")
        t = getattr(self, "_spec_prebuild_task", None)
        if t is not None:
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass
        await self.balancer.stop()
        if self._generate_client is not None:
            try:
                # drops its pinned prefix sessions, then closes its session
                await self._generate_client.__aexit__(None, None, None)
            except Exception:
                pass
            self._generate_client = None
        if self.chaos is not None:
            # stalled (slow-loris) handlers never finish on their own —
            # they must not hold the graceful drain below hostage
            self.chaos.cancel_stalls()
        if self._runner:
            # stop accepting + drain in-flight requests BEFORE the session
            # export: a chunk completing after the export snapshot would be
            # missing from the handed-off copy and 409 the failed-over
            # client into a restart
            await self._runner.cleanup()
        # graceful shutdown hands live session KV to surviving same-stage
        # replicas (the same machinery as migration handoff), so a client
        # that fails over to another entry continues WITHOUT a session
        # restart. Best effort: a crash (no stop()) still loses the KV and
        # falls back to the client's restart path.
        await self._export_and_handoff(self.executor, self.info.stage)
        if self._http:
            await self._http.close()
        await self.dht.stop()
        self.scheduler.shutdown()
        self.journal.emit("node.stop", stage=self.info.stage)
        self._flush_obs()  # final flush: the merge/postmortem CLIs read these
        self._stopped.set()

    async def _export_and_handoff(self, executor, stage: int) -> None:
        """Export `executor`'s live session KV and ship it to the remaining
        replicas of `stage` (shared by graceful stop() and change_stage
        migration). Best effort: failures degrade to client restarts."""
        export = getattr(executor, "export_sessions", None)
        if export is None or self._http is None:
            return
        try:
            loop = asyncio.get_running_loop()
            exported = await loop.run_in_executor(None, export)
            if exported:
                await self._handoff_sessions(exported, stage)
        except Exception:
            log.exception("session handoff failed (clients will restart)")

    # ------------------------------------------------------------- announce

    def _advertised_sessions(self) -> list:
        """Hashes of the sessions whose KV lives HERE — gossiped in this
        node's record so a peer (a failed-over entry, a mid-chain relay)
        can route a session's next chunk to the replica actually holding
        it instead of 409ing into a client restart."""
        store = getattr(self.executor, "sessions", None)
        ids_fn = getattr(store, "ids", None)
        if not callable(ids_fn):
            return []
        # keep the NEWEST 128 (insertion order) — a just-adopted handoff
        # session must make the advert, or the failed-over client that the
        # handoff exists for can't find it
        return sorted(sess_hash(s) for s in ids_fn()[-128:])

    def _advertised_standby(self) -> list:
        """Hashes of the sessions whose REPLICATED (shadow) KV lives here
        — gossiped as `standby` so the rescue path can find a promotion
        target when no live `sess` holder remains. Only ever present
        with --standby-repl on: a disabled node's gossip record stays
        byte-identical to a build without the replication plane."""
        if self.standby is None:
            return []
        return sorted(sess_hash(s) for s in self.standby.ids()[-128:])

    def _windowed_gossip(self) -> Dict[str, float]:
        """TRAILING-WINDOW hop/service quantiles for gossip and /health
        (obs.tsdb, last 60 s) — replacing the all-time numbers PR 3
        gossiped: a replica that was slow an hour ago and recovered must
        stop reporting an elevated p99 within the window horizon, or
        routing and outlier detection act on history instead of now.
        Cached ~1 s (announce() runs per load change); the inline
        sample() keeps the window current between telemetry ticks
        (mid-bucket samples merge idempotently). Keys are omitted when
        the window holds no observations — never backfilled from the
        cumulative histograms."""
        now = time.monotonic()
        ts, cached = self._windowed_cache
        if cached is not None and now - ts < 1.0:
            return cached
        self.tsdb.sample()
        out: Dict[str, float] = {}
        hq = self.tsdb.trailing_quantiles("hop.relay_ms", self.window_s)
        if hq is not None:
            out["hop_p50_ms"] = hq["p50_ms"]
            out["hop_p99_ms"] = hq["p99_ms"]
        # trailing stage-compute p99: the outlier detector's fallback
        # comparison field — last-stage replicas relay nothing, so they
        # have no hop series to compare on (obs.canary.detect_outliers)
        sq = self.tsdb.trailing_quantiles(
            "stage.compute_ms", self.window_s, qs=(0.99,)
        )
        if sq is not None:
            out["svc_p99_ms"] = sq["p99_ms"]
        self._windowed_cache = (now, out)
        return out

    def _canary_targets(self):
        """Current entry-replica candidates for the canary prober: the
        gossiped stage-0 records (every chain starts there)."""
        return sorted(
            (str(v["host"]), int(v["port"]))
            for v in self.dht.get_stage(0).values()
            if v.get("host") and v.get("port")
        )

    def _prof_target(self) -> Optional[proflib.AnatomyTarget]:
        """Live AnatomyTarget from the CURRENT executor (rebinding per
        call, so a stage migration's swapped-in executor profiles its own
        weights), or None when the executor can't express one."""
        fn = getattr(self.executor, "anatomy_target", None)
        if not callable(fn):
            return None
        try:
            return proflib.AnatomyTarget(quant=self.quant, **fn())
        except Exception:
            log.debug("anatomy target unavailable", exc_info=True)
            return None

    def _setup_prof(self) -> None:
        """Build the live-anatomy plane (obs.prof) over the current
        executor. Priors (--prof-priors) key on (chip, preset, quant,
        stage) — a replica without a matching prior still publishes the
        anatomy/roofline series; only the sentinel skips."""
        if self._prof_target() is None:
            log.info(
                "live anatomy disabled: executor %s has no anatomy_target",
                type(self.executor).__name__,
            )
            return
        priors = {}
        if self.prof_priors:
            try:
                priors = proflib.load_priors(self.prof_priors)
            except (OSError, ValueError) as e:
                log.warning("prof priors %s unusable: %s", self.prof_priors, e)
        # detect the chip EAGERLY (the executor already initialized the
        # backend): a history flushed before the first idle tick must not
        # stamp chip="cpu" on a TPU node — the offline sentinel would
        # judge TPU per-token cost against a CPU prior
        from inferd_tpu.perf import roofline as rl

        chip = rl.detect_chip()
        self.prof = proflib.LiveAnatomy(
            self.metrics,
            self._prof_target,
            # no history_fn: the tick thread must not serialize the live
            # rings itself — _prof_loop snapshots on the loop thread and
            # passes the snapshot into tick_once
            journal=self.journal,
            device_lock=self._capture_lock,
            executor_lock_fn=lambda: getattr(self.executor, "_dev_lock", None),
            busy_fn=lambda: self.scheduler.inflight > 0,
            priors=priors,
            chip=chip,
            key_fn=lambda: proflib.prior_key(
                chip.key, self.cfg.name, self.quant, self.info.stage,
            ),
        )
        # stamp the sentinel's identity into the history meta so the
        # OFFLINE check (obs prof --check over --trace-dir dumps) can
        # match each node's history against the same priors table
        self.tsdb.meta.update(
            preset=self.cfg.name, quant=self.quant, chip=chip.key,
        )
        self._prof_task = asyncio.create_task(self._prof_loop())

    async def _prof_loop(self) -> None:
        """Low-duty-cycle live-anatomy tick (obs.prof): one phase scan
        per interval, off the event loop, only when the node is idle and
        no capture holds the device. A sentinel transition re-announces
        urgently so the gossiped `perf` flag propagates within a gossip
        period, mirroring the outlier flag."""
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(self.prof_interval_s)
            try:
                # serialize the history snapshot HERE, on the loop thread
                # where sample() also runs — the tick thread must never
                # iterate the live rings concurrently with a sample
                self.tsdb.sample()
                h = self.tsdb.history()
                out = await loop.run_in_executor(
                    None, self.prof.tick_once, h
                )
                if out.get("sentinel_changed"):
                    self._health_cache = (0.0, None)
                    self.announce()
            except Exception:
                log.exception("live-anatomy tick failed")

    async def _tsdb_loop(self) -> None:
        """Fixed-cadence telemetry tick: fold the registry into the
        windowed rings every `tsdb_period_s` (so idle periods age the
        window out instead of freezing it), refresh gauges every 5th
        tick, and every 2nd tick run replica-outlier self-detection and
        re-announce (non-urgent — the gossip loop carries it): the
        gossiped trailing quantiles must keep tracking the window even
        when no load change triggers an announce, or peers would compare
        against quantiles frozen at each node's last request."""
        tick = 0
        while True:
            await asyncio.sleep(self.tsdb_period_s)
            tick += 1
            try:
                if tick % 5 == 0 and eventslib.enabled():
                    self._update_gauges()
                self.tsdb.sample()
                if tick % 2 == 0:
                    self._check_outlier()
                    self.announce(urgent=False)
            except Exception:
                log.exception("telemetry tick failed")

    def _check_outlier(self) -> None:
        """Flag THIS node when its trailing p99 diverges >= k*MAD from
        its stage peers' (obs.canary.detect_outliers over the gossiped
        windowed quantiles, own record overlaid with the freshest local
        window). Transitions journal `replica.outlier`/`.outlier_cleared`
        and re-announce urgently so the gossiped `outlier` flag — and the
        routing penalty every peer applies to it — propagates within a
        gossip period, not a cache lifetime."""
        if not eventslib.enabled():
            self._outlier_info = None
            return
        stage_map = {
            nid: dict(rec)
            for nid, rec in self.dht.get_stage(self.info.stage).items()
        }
        own = stage_map.setdefault(self.info.node_id, {})
        own.update(self._windowed_gossip())
        info = canarylib.detect_outliers(stage_map).get(self.info.node_id)
        was = self._outlier_info is not None
        self._outlier_info = info
        if info is not None and not was:
            self.journal.emit(
                "replica.outlier", stage=self.info.stage,
                field=info["field"], value=round(info["value"], 3),
                median=round(info["median"], 3), mad=round(info["mad"], 3),
            )
        elif info is None and was:
            self.journal.emit(
                "replica.outlier_cleared", stage=self.info.stage
            )
        if (info is not None) != was:
            self._health_cache = (0.0, None)  # gossip carries the flag
            self.announce()

    def _cobatch_mean(self) -> Optional[float]:
        """Mean co-batch size of this node's stage window (None when the
        node doesn't window) — gossiped so the dashboard shows batching
        effectiveness per node with zero extra round trips."""
        win = getattr(getattr(self, "executor", None), "window", None)
        if win is None:
            return None
        return win.stats()["mean_batch"]

    def _health_state(self) -> Dict[str, Any]:
        """SLO verdict over this node's own registry + journal + gossiped
        peers, plus the obs gossip fields derived from the same snapshot
        (health column, hbm%, compile count for the dashboard). Cached
        ~1 s: announce() runs per load change and must not re-evaluate
        the rule set (or re-scrape device memory) each time."""
        now = time.monotonic()
        ts, cached = self._health_cache
        if cached is not None and now - ts < 1.0:
            return cached
        self._update_gauges()
        self.tsdb.sample()
        snap = self.metrics.snapshot()
        # TRAILING-WINDOW histogram summaries replace the all-time ones
        # for rule evaluation: `hop.relay_ms.p99_ms < 2000` must judge
        # the last minute, not the process's whole life — a recovered
        # node stops firing within the window horizon. A histogram with
        # no observations inside the window resolves to nothing, so its
        # rules SKIP (no data is not green).
        trailing: Dict[str, Any] = {}
        for name in snap["histograms"]:
            s = self.tsdb.trailing_summary(name)
            if s is not None:
                trailing[name] = {k: round(v, 3) for k, v in s.items()}
        rule_snap = dict(snap, histograms=trailing)
        peers: Dict[str, Dict[str, Any]] = {}
        for stage_map in self.dht.get_all(self.info.num_stages).values():
            for nid, rec in stage_map.items():
                if nid != self.info.node_id:
                    peers[nid] = rec
        # events=None (not []) when the journal is killed: event rules
        # must SKIP (no data), not evaluate against a silent ring —
        # metric-only rules (queue.depth, hop p99, trace.dropped, hbm)
        # keep working so INFERD_EVENTS=0 doesn't blind the SLO engine
        verdict = healthlib.evaluate(
            healthlib.DEFAULT_RULES, rule_snap,
            events=self.journal.events() if eventslib.enabled() else None,
            peers=peers,
            histories=[self.tsdb.history()],
        )
        gossip: Dict[str, Any] = {"health": verdict["status"]}
        if self._outlier_info is not None:
            # self-detected replica outlier: peers' routing applies
            # OUTLIER_PENALTY to this record (control/path_finder, dstar)
            gossip["outlier"] = 1
        if self.prof is not None:
            # continuous profiling plane (obs.prof): the live roofline
            # fraction + the sentinel flag — old peers pass the unknown
            # keys through untouched (mixed-version contract), old
            # dashboards/collectors render the cells blank
            if self.prof.last_live_frac is not None:
                gossip["roofline"] = round(self.prof.last_live_frac, 4)
            if self.prof.sentinel_fired:
                gossip["perf"] = 1
        frac = snap["gauges"].get("hbm.frac")
        if frac is not None:
            gossip["hbm"] = round(float(frac), 3)
        # short-window availability burn (obs.health.burn_gauges, already
        # refreshed into the registry by _update_gauges): gossiped so
        # fleet controllers (control.autoscale, tools/collector) see
        # which stage is burning user error budget without scraping
        # every node — the SLO-side scale-up trigger next to kvfree
        burn = snap["gauges"].get("burn.availability")
        if burn is not None:
            gossip["burn"] = round(float(burn), 2)
        # trailing-window prefix-cache hit rate (memory-plane SLI): the
        # collector's per-stage `cachehit` column and the dashboard cell;
        # omitted when the window saw no prompt traffic (windowed
        # semantics — never a frozen ratio), on dense executors, and with
        # events disabled (the kv.* series don't exist then)
        ch = self._cachehit_frac()
        if ch is not None:
            gossip["cachehit"] = ch
        compiles = snap["counters"].get("compile.events")
        if compiles:
            gossip["compiles"] = int(compiles)
        cached = {"verdict": verdict, "gossip": gossip}
        self._health_cache = (now, cached)
        return cached

    def _kvfree_frac(self) -> Optional[float]:
        """Paged-KV block-pool free fraction (blocks_free / num_blocks) —
        gossiped as `kvfree` so fleet controllers see the MEMORY capacity
        signal PR 10's admission shed gates on locally: a replica about
        to shed is about to shed no matter what its lane load says. The
        same watermark feeds control.autoscale's scale-up trigger. None
        (key omitted) on dense executors — absent is not 1.0."""
        pool = getattr(self.executor, "pool", None)
        if pool is None:
            return None
        try:
            total = int(pool.num_blocks)
            free = int(pool.blocks_free)
        except Exception:
            return None
        return round(free / total, 4) if total else None

    def announce(self, urgent: bool = True) -> None:
        sess = self._advertised_sessions()
        stand = self._advertised_standby()
        wq = self._windowed_gossip()
        cb = self._cobatch_mean()
        kvfree = self._kvfree_frac()
        pfx = self._prefix_digest()
        ada = self._adapter_digest()
        shedding = self._pool_under_reserve() is not None
        obs_gossip = (
            self._health_state()["gossip"]
            if eventslib.enabled() and hasattr(self, "scheduler") else {}
        )
        self.dht.announce(
            {
                "name": self.info.name,
                "stage": self.info.stage,
                "load": self.scheduler.inflight if hasattr(self, "scheduler") else 0,
                "cap": self.info.capacity,
                "host": self.info.host,
                "port": self.info.port,
                "model": self.info.model_name,
                **(
                    {"svc_ms": round(self._svc_ewma, 3)}
                    if self._svc_ewma is not None
                    else {}
                ),
                # trailing-window quantiles (_windowed_gossip): same key
                # names PR 3 gossiped, windowed semantics — old peers
                # read them unchanged, plus the new svc_p99_ms which
                # they (and any other unknown key) simply ignore
                **wq,
                **({"cobatch": cb} if cb is not None else {}),
                # block-pool free fraction: a control-plane capacity
                # signal (ungated — it must survive INFERD_EVENTS=0,
                # like load/cap); old peers ignore the unknown key
                **({"kvfree": kvfree} if kvfree is not None else {}),
                # memory-plane routing signals (ungated, like kvfree):
                # `pfx` = the prefix-index digest entry routers score
                # cache affinity against (core.prefix.make_digest);
                # `shed` = currently under the admission watermark, so
                # routers suppress the affinity bonus and penalize
                # affinity-scored picks here. Old peers pass both keys
                # through bit-true and ignore them (the PR 7 mixed-
                # version gossip contract).
                **({"pfx": pfx} if pfx else {}),
                # resident-adapter digest (multi-tenant LoRA, the `pfx`
                # pattern): bounded name list routers score adapter
                # affinity against (runtime/adapters.AdapterAffinity).
                # OMITTED without --adapters (the kill-switch contract
                # keeps disabled records byte-identical) but PRESENT —
                # `[]` — with an empty registry: key presence marks
                # adapter capability for handoff/standby target picks;
                # old peers pass the key through bit-true
                **({"ada": ada} if ada is not None else {}),
                **({"shed": 1} if shedding else {}),
                **obs_gossip,
                # drain flag: both routers (min-load ranked pick and the
                # D*-Lite planner) treat it as an exclusion; old peers
                # ignore the unknown key and keep routing here — drain
                # converges at fleet-upgrade speed, never breaks mixed
                **({"draining": 1} if self._draining else {}),
                **({"sess": sess} if sess else {}),
                # replicated-session advert (crash-tolerant sessions):
                # ONLY emitted with --standby-repl on AND shadows held —
                # the kill-switch contract keeps disabled records
                # byte-identical. Old peers ignore the unknown key.
                **({"standby": stand} if stand else {}),
            },
            urgent=urgent,
        )

    def _announce_load(self) -> None:
        # per-request load tick: update the local record only; the 1 s
        # gossip loop carries it (keeps serialization + UDP off the hot path)
        self.announce(urgent=False)

    def _obs_file(self, suffix: str) -> Optional[str]:
        if not self.trace_dir:
            return None
        return os.path.join(
            self.trace_dir,
            self.info.node_id.replace(":", "_") + suffix,
        )

    def _span_file(self) -> Optional[str]:
        return self._obs_file(".spans.jsonl")

    def _flush_obs(self) -> None:
        """Flush the per-node observability artifacts the offline CLIs
        (merge, health, postmortem) consume: new spans and journal events
        append to their JSONL files WITHOUT draining the rings — /spans,
        /events, and the gossiped summaries must keep seeing the recent
        buffers between flushes — and one metrics snapshot line appends
        per flush (the incident report's "metrics window")."""
        path = self._span_file()
        if path is None:
            return
        try:
            self.tracer.flush_jsonl(path)
        except OSError:
            log.exception("span dump to %s failed", path)
        if not eventslib.enabled():
            return
        try:
            self.journal.flush_jsonl(self._obs_file(".events.jsonl"))
            self._update_gauges()
            line = json.dumps(
                {
                    "ts": tracelib.now(),
                    "service": self.info.node_id,
                    **self.metrics.snapshot(),
                },
                separators=(",", ":"),
            )
            with open(self._obs_file(".metrics.jsonl"), "a") as f:
                f.write(line + "\n")
            # windowed-history dump (OVERWRITTEN, not appended — the
            # rings carry their own retention): the offline half of the
            # fleet SLI pipeline (`obs fleet`, `obs health --check` burn
            # rules) reads these next to the span/event files. Written
            # via rename so a kill mid-dump can't leave a truncated file
            self.tsdb.sample()
            hist_path = self._obs_file(".history.json")
            with open(hist_path + ".tmp", "w") as f:
                json.dump(self.tsdb.history(), f, separators=(",", ":"))
            os.replace(hist_path + ".tmp", hist_path)
        except OSError:
            log.exception("journal/metrics dump failed")

    async def _sweep_loop(self, period_s: float = 30.0) -> None:
        """Collect orphaned sessions: executor KV caches past their idle TTL
        and stale session-affinity entries. Also flushes the span ring to
        the per-node JSONL file so a long trace outlives the ring cap."""
        while True:
            await asyncio.sleep(period_s)
            try:
                sessions = getattr(self.executor, "sessions", None)
                if sessions is not None:
                    dropped = sessions.sweep()
                    if dropped:
                        self.metrics.inc("sessions.swept", dropped)
                if self.standby is not None:
                    swept = self.standby.sweep()
                    if swept and eventslib.enabled():
                        self.metrics.inc("repl.standby_swept", swept)
                cutoff = time.monotonic() - 3600.0
                while self._session_next:
                    key, (_, ts) = next(iter(self._session_next.items()))
                    if ts >= cutoff:
                        break
                    self._session_next.popitem(last=False)
                self._flush_obs()
            except Exception:
                log.exception("session sweep failed")

    # ------------------------------------------------------------- handlers

    async def handle_forward(self, request: web.Request) -> web.Response:
        t0 = time.perf_counter()
        try:
            env = wire.unpack(await request.read())
        except Exception as e:
            return self._error_response(400, f"bad envelope: {e}")
        if isinstance(env, dict) and env.get(wire.MULTI_KEY) is not None:
            return await self._handle_multi_forward(env, t0)
        return await self._forward_one(env, t0)

    async def _handle_multi_forward(self, env, t0: float) -> web.Response:
        """A coalesced relay envelope: N sessions' decode activations in
        one POST (wire.coalesce_forward). Fan the frames back out into
        single-session envelopes and run them CONCURRENTLY through the
        ordinary forward path — on a windowed executor they co-arrive and
        co-batch into one device step; every other path (rescue, re-route,
        chain) applies per frame unchanged. The reply is one multi
        envelope carrying each frame's packed reply + status."""
        try:
            frames = wire.split_forward(env)
        except Exception as e:
            return self._error_response(400, f"bad multi envelope: {e}")
        self.metrics.inc("forward.multi_envelopes")
        self.metrics.inc("forward.multi_frames", len(frames))
        resps = await asyncio.gather(
            *(self._forward_one(f, t0) for f in frames)
        )
        multi = [
            {"status": r.status, "body": bytes(r.body or b"")} for r in resps
        ]
        return web.Response(body=wire.pack({wire.MULTI_KEY: multi}))

    async def _forward_one(self, env, t0: float) -> web.Response:
        if not tracelib.enabled():
            return await self._forward_inner(env, t0, None)
        # server umbrella span for this hop: parented to the `trace` key
        # riding the envelope (a client step span or an upstream relay
        # span — its send/recv pair brackets this span for the merge
        # CLI's skew correction); queue/compute/relay children hang off it
        parent = tracelib.SpanContext.from_wire(env.get(tracelib.WIRE_KEY))
        tin = tracelib.SpanContext(
            parent.trace_id if parent is not None else tracelib.new_id(),
            tracelib.new_id(),
        )
        t_wall = tracelib.now()
        try:
            return await self._forward_inner(env, t0, tin)
        finally:
            try:
                stage_attr = int(env.get("stage", 0))
            except (TypeError, ValueError):
                stage_attr = -1
            self.tracer.record_span(
                "forward", "server", t_wall, tracelib.now(),
                parent=parent, ctx=tin, attrs={"stage": stage_attr},
            )

    async def _forward_inner(
        self, env: Dict[str, Any], t0: float,
        tin: Optional[tracelib.SpanContext],
    ) -> web.Response:
        stage = int(env.get("stage", 0))
        session_id = env.get("session_id") or str(uuid.uuid4())
        task_id = env.get("task_id") or str(uuid.uuid4())
        # end-to-end deadline riding the envelope (absent on deadline-less
        # traffic and from old peers — behavior is then identical to
        # before deadlines existed). An EXPIRED budget fast-fails with
        # the typed non-retryable `deadline` code BEFORE any relay,
        # rescue bounce, or compute: a request that cannot make it back
        # in time must stop consuming the chain's work.
        deadline_ms = env.get(retrylib.DEADLINE_KEY)
        rem = retrylib.remaining_s(deadline_ms)
        if rem is not None and rem <= 0:
            return self._deadline_response(tin, session_id, stage, "entry")

        if stage != self.info.stage:
            self.metrics.inc("forward.mismatch")
            if not env.get("relay", True):
                # chain mode promises a FIXED topology: a mismatch means the
                # client's server_addrs list is stale (this node migrated) or
                # misordered. Rerouting via the DHT would silently violate
                # that contract and orphan the session's KV on a replica the
                # client will never address again — fail loudly instead.
                return self._error_response(
                    409,
                    f"wrong stage: this node serves {self.info.stage}, not {stage}",
                    code="wrong_stage",
                )
            # wrong node for this stage: relay to a correct one (reference
            # node.py:139-141), excluding ourselves to avoid a loop
            try:
                return await self._relay(
                    env, stage, exclude={self.info.node_id}, tin=tin,
                    span_attrs={"mismatch": True},
                )
            except NoNodeForStage as e:
                if stage != self.info.stage:
                    return self._error_response(503, str(e))
                # the empty-stage recovery hook migrated *us* to this stage
                # during the retry loop — serve the request locally

        try:
            start_pos = int(env.get("payload", {}).get("start_pos", -1))
        except (TypeError, ValueError, AttributeError):
            start_pos = -1  # malformed payloads fail in the guarded compute

        if start_pos == 0:
            # ADMISSION CONTROL: a brand-new session asks this replica to
            # allocate KV it will hold for the session's whole life —
            # shed it (typed 503 + a Retry-After pacing hint derived from
            # window occupancy) while draining or while the paged block
            # pool is under its free-watermark reserve. Mid-session
            # chunks (start_pos > 0) are never shed here: their KV is
            # already resident and finishing them RELEASES capacity.
            shed = self._admission_shed()
            if shed is not None:
                code, msg = shed
                ra = self._retry_after_s()
                self.metrics.inc("admission.shed")
                self.journal.emit(
                    "admission.shed", trace=tin, session=session_id,
                    stage=stage, code=code, retry_after=ra,
                )
                return self._error_response(
                    503, msg, code=code, retry_after=ra
                )

        if (
            env.get("relay", True)
            and "route" not in env
            and start_pos == 0
            and stage + 1 < self.info.num_stages
        ):
            # NEW session entering here: plan the whole downstream chain via
            # the incremental D*-Lite planner; the route rides the envelope
            # so every relay hop follows the planned replica (affinity then
            # pins it). Planning failure (e.g. an empty stage mid-recovery)
            # falls back to the per-hop min-load pick. A tenant session's
            # adapter earns downstream holders the bounded affinity bonus
            # (runtime/adapters.AdapterAffinity through dstar.node_cost) —
            # a miss just hot-loads there, so the bonus is pure savings.
            ad_key = (env.get("payload") or {}).get("adapter")
            affinity = None
            if ad_key is not None:
                from inferd_tpu.runtime.adapters import AdapterAffinity

                affinity = AdapterAffinity(str(ad_key))
            route = self._plan_route(stage + 1, affinity=affinity)
            if route:
                env["route"] = route

        if (
            env.get("relay", True)
            and not env.get("rescued")
            and start_pos > 0
            and env.get("session_id") is not None
            and not self._holds_session(session_id)
        ):
            # mid-session chunk landed on a replica WITHOUT its KV (a client
            # failed over to a different entry, or a relay's affinity map
            # died with it). The gossip record of the replica actually
            # holding the session advertises it — relay DIRECTLY there
            # instead of 409ing the client into a full restart; with no
            # live `sess` holder, a peer advertising the session under
            # `standby` (async KV replication — runtime/repl) is the
            # promotion target. The "rescued" marker caps this at ONE
            # bounce: a stale advert of a dead holder must not ping-pong
            # between surviving replicas. Short retry loop: the chunk may
            # be RACING a dying node's graceful handoff — within ~1 s the
            # KV lands on a surviving replica (possibly this one) and the
            # chunk proceeds. Bounce count: --rescue-bounces.
            attempts = 0
            last_rescue_err = "no holder advertised"
            for rescue_attempt in range(self.rescue_bounces):
                if self._holds_session(session_id):
                    break  # the handoff landed HERE: serve locally below
                rem = retrylib.remaining_s(deadline_ms)
                if rem is not None and rem <= 0:
                    # the end-to-end budget died while we waited out the
                    # handoff: stop bouncing dead work around the stage
                    return self._deadline_response(
                        tin, session_id, stage, "rescue"
                    )
                if rescue_attempt and not self.retry_budget.try_acquire():
                    # rescue re-relays are retries too: the shared bucket
                    # bounds a dead stage's blind-bounce rate (the first
                    # lookup each request stays free — budgets bound
                    # AMPLIFICATION, not recovery itself)
                    self.metrics.inc("rescue.budget_denied")
                    last_rescue_err = "rescue retry budget denied"
                    break
                attempts = rescue_attempt + 1
                holder = self._gossip_session_holder(
                    session_id, stage, exclude={self.info.node_id}
                )
                standby_kind = holder is None
                if standby_kind:
                    # no live holder advertises the session: a standby
                    # replica may hold its replicated prefix — relaying
                    # there lets it PROMOTE (or offer the client a
                    # bounded resume) instead of 409ing into a restart
                    holder = self._gossip_standby_holder(
                        session_id, stage, exclude={self.info.node_id}
                    )
                if holder is not None:
                    self.metrics.inc("sessions.rescue_relay")
                    # flight recorder: a rescue is the fleet ACTING on a
                    # dead/moved replica — postmortems interleave this
                    # with the peer.dead that caused it
                    self.journal.emit(
                        "session.rescue", trace=tin, session=session_id,
                        stage=stage, holder=holder,
                        attempt=rescue_attempt,
                        **({"standby": 1} if standby_kind else {}),
                    )
                    try:
                        t_resc = time.perf_counter()
                        resp = await self._relay(
                            {**env, "rescued": True}, stage,
                            exclude={self.info.node_id}, prefer=holder,
                            tin=tin, phase="rescue", attempts=1,
                        )
                        # rescue bounces belong in the hop-latency series
                        # too (the old span-derived gossip quantiles
                        # covered relay AND rescue phases): a replica
                        # whose forwards constantly fail over through
                        # slow rescues must not gossip a healthy hop p99
                        self.metrics.observe(
                            "hop.relay_ms",
                            (time.perf_counter() - t_resc) * 1e3,
                        )
                    except NoNodeForStage:
                        resp = None
                        last_rescue_err = "no node for stage"
                    if resp is not None and resp.status < 500:
                        if standby_kind:
                            # the standby ANSWERED (a promotion, or the
                            # typed resume offer the client acts on):
                            # repoint affinity so the session's next
                            # chunks go straight there instead of
                            # re-discovering it per chunk
                            key = (session_id, stage)
                            self._session_next[key] = (
                                holder, time.monotonic()
                            )
                            self._session_next.move_to_end(key)
                        return resp
                    last_rescue_err = (
                        f"holder {holder} answered {resp.status}"
                        if resp is not None
                        else f"holder {holder} unreachable"
                    )
                    # dead/stale holder: wait out the handoff and re-check
                if self._standby_len(session_id, stage) is not None:
                    # the advertised holder is gone (or nothing advertises
                    # the session at all — e.g. the crashed primary's
                    # record already TTL'd) and WE hold the replicated
                    # prefix FOR THIS STAGE: stop waiting out the bounce
                    # budget — every sleep here is pure added RTO — and
                    # promote locally
                    break
                await asyncio.sleep(0.15)
            if (
                not self._holds_session(session_id)
                and self._standby_len(session_id, stage) is None
            ):
                # the fleet STOPPED acting: the give-up must be visible
                # in postmortems next to the peer.dead that caused it —
                # falling silently into the client's 409 reads as "the
                # swarm never noticed" (the one-bounce end_session twin
                # stays silent by design: freeing KV early is pure
                # housekeeping, nothing user-visible was lost)
                self.metrics.inc("sessions.rescue_failed")
                self.journal.emit(
                    "session.rescue_failed", trace=tin,
                    session=session_id, stage=stage, attempts=attempts,
                    error=last_rescue_err,
                )
            # no holder materialized: serve locally -> 409 -> restart

        if (
            start_pos > 0
            and env.get("session_id") is not None
            and not self._holds_session(session_id)
        ):
            # standby promotion (crash-tolerant sessions): THIS node holds
            # the session's replicated KV prefix — either promote it into
            # the executor and serve the chunk (start_pos inside the
            # frontier: the replay-rollback protocol recomputes the
            # overlap deterministically), or answer the typed resume
            # offer so the client re-prefills ONLY the tokens past the
            # frontier instead of the whole context. Runs for rescued
            # relays and direct failovers alike; a stale/partial shadow
            # degrades to the ordinary 409/restart path below — never a
            # divergent token.
            promo = await self._promote_or_offer(
                session_id, stage, start_pos, tin
            )
            if promo is not None:
                return promo

        self.metrics.inc("forward.requests")
        if self.chaos is not None:
            try:
                await self.chaos.before_forward()
            except ChaosDrop as e:
                self.metrics.inc("chaos.dropped")
                return self._error_response(500, str(e))
        t_q = tracelib.now()  # queue-span anchor: enqueue -> worker pickup
        # bind the executor NOW: a request that passed the stage check
        # must compute on the executor of that stage even if a
        # migration swaps self.executor while this request waits in the
        # scheduler queue (the swapped-in executor serves a DIFFERENT
        # stage — its process() would reject or, worse, mis-shape)
        executor = self.executor
        _pl = env.get("payload")
        if (
            isinstance(_pl, dict) and _pl.get("adapter") is not None
            and getattr(executor, "adapters", None) is None
        ):
            # a tenant-addressed chunk on a replica with no registry:
            # LOUD deterministic reject — serving the base model instead
            # would be silent tenant corruption (the lane executors raise
            # this themselves; this guard covers solo/mesh/counter)
            return self._error_response(
                409,
                f"payload names adapter {_pl.get('adapter')!r} but this "
                "replica serves no adapter registry (--adapters)",
                code="no_adapter_registry",
            )
        # stage-level continuous batching: single-token decode steps join
        # the executor's arrival window; co-arrivals run as ONE device
        # step and their relays coalesce (see _run_stage_window)
        use_window = (
            getattr(executor, "window", None) is not None
            and _is_decode_step(env.get("payload"))
        )
        try:
            if use_window:
                win_res = await self.scheduler.run(
                    executor.window.submit, (session_id, env, tin, t_q)
                )
            else:
                result, pure_ms, w0, w1 = await self.scheduler.run(
                    self._timed_process, executor, session_id,
                    env.get("payload", {}),
                )
        except BufferError as e:  # KV budget exceeded: deterministic
            # the executors' BufferError now names the session AND lane
            # (core.cache.ensure_room owner contract): the journal event
            # and the 409 the client sees carry the SAME identity
            self.journal.emit(
                "kv.overflow", trace=tin, session=session_id, stage=stage,
                error=str(e),
            )
            return self._error_response(409, str(e), code="overflow")
        except RuntimeError as e:
            from inferd_tpu.runtime.adapters import AdapterCapacityError
            from inferd_tpu.runtime.batch_executor import CapacityError

            if isinstance(e, (CapacityError, AdapterCapacityError)):
                # transient backpressure (busy lanes / every adapter slot
                # held by live sessions or pins): retryable 503
                return self._error_response(503, str(e), code="busy")
            log.exception("stage compute failed")
            self._maybe_oom_event(e, tin, stage)
            return self._error_response(500, str(e))
        except ValueError as e:
            from inferd_tpu.runtime.adapters import UnknownAdapterError

            if isinstance(e, UnknownAdapterError):
                # a name outside this node's --adapters catalog is a
                # permanent config error: a typed non-retryable code,
                # never the restart-and-retry `session_state` loop
                return self._error_response(409, str(e), code="unknown_adapter")
            # out-of-order/replayed chunk — the session's KV here doesn't
            # match (e.g. its replica died and we're a fresh pick); a client
            # restarting with a new session recovers
            return self._error_response(409, str(e), code="session_state")
        except Exception as e:  # compute failure
            log.exception("stage compute failed")
            self._maybe_oom_event(e, tin, stage)
            return self._error_response(500, f"stage compute failed: {e}")
        if use_window:
            if win_res[0] == "relayed":
                # the window flusher already relayed this entry (possibly
                # coalesced with its co-batch) and holds the reply body
                _, status, body = win_res
                return web.Response(status=status, body=body)
            # local result (final stage / chain mode): the flusher recorded
            # the window+compute spans and the svc EWMA — fall through to
            # the shared response shaping below
            result = win_res[1]
            # windowed entries are single-token DECODE steps, which never
            # carry tokens_saved — popped anyway so the strip-before-wire
            # contract holds uniformly if that invariant ever moves
            saved = (
                int(result.pop("tokens_saved", 0))
                if isinstance(result, dict) else 0
            )
        else:
            # per-request shared-prefix saving (paged executors stamp it
            # on prefill results): popped here so relayed payloads stay
            # byte-identical to pre-digest builds; re-attached to FINAL
            # results below so the caller sees its own tokens_saved
            saved = (
                int(result.pop("tokens_saved", 0))
                if isinstance(result, dict) else 0
            )
            self.metrics.observe(
                "stage.compute_ms", (time.perf_counter() - t0) * 1e3
            )
            if eventslib.enabled():
                # per-stage token-throughput counter (every chain stage
                # touches every token — the fleet aggregator sums LAST
                # stages only, obs.fleet): K for a fused K-step result,
                # 1 per ordinary step/prefill chunk
                self.metrics.inc(
                    "stage.tokens",
                    len(result["tokens"][0])
                    if isinstance(result, dict) and "tokens" in result
                    else 1,
                )
            if tin is not None:
                # host-side span pair for this hop: worker-pool wait, then
                # the executor's pure compute (wall stamps from the worker)
                self.tracer.record_span(
                    "queue", "queue", t_q, w0, parent=tin,
                    attrs={"stage": stage},
                )
                self.tracer.record_span(
                    "compute", "compute", w0, w1, parent=tin,
                    # a prefill that mapped cached prefix blocks carries
                    # how many tokens it SKIPPED — per-request memory-
                    # plane attribution in merged timelines
                    attrs={"stage": stage, "ms": round(pure_ms, 3),
                           **({"tokens_saved": saved} if saved else {})},
                )
            # service-time EWMA: announced as svc_ms, feeding every
            # planner's measured-latency edge-cost term (carried by the 1 s
            # gossip loop). PURE compute time (timed inside the worker):
            # queue wait is already the load/cap term of node_cost —
            # folding it in here too would double-charge queued nodes and
            # amplify route herding.
            self._svc_ewma = (
                pure_ms if self._svc_ewma is None
                else 0.8 * self._svc_ewma + 0.2 * pure_ms
            )

        if not env.get("relay", True):
            # chain mode (hub-and-spoke): the CLIENT drives each stage in
            # turn and carries activations between them — the reference's
            # gRPC slice topology (/root/reference/models/qwen3/client/
            # rpc_client.py:46-57) behind the same endpoint. Return this
            # stage's raw result instead of relaying it onward.
            if saved and isinstance(result, dict):
                result["tokens_saved"] = saved
            return web.Response(
                body=wire.pack(
                    {
                        "task_id": task_id,
                        "session_id": session_id,
                        "stage": stage,
                        "result": result,
                        "served_by": self.info.node_id,
                    }
                )
            )

        if self._is_final(result):
            if saved:
                # the caller's own per-request SLI: how much prefill its
                # prompt skipped on this replica (key absent on cold
                # prefills and old builds — additive wire change)
                result["tokens_saved"] = saved
            resp = {
                "task_id": task_id,
                "session_id": session_id,
                "result_for_user": result,
                "served_by": self.info.node_id,
            }
            return web.Response(body=wire.pack(resp))

        rem = retrylib.remaining_s(deadline_ms)
        if rem is not None and rem <= 0:
            # the budget died DURING compute: relaying the activations
            # downstream would be dead work for every remaining stage —
            # this check is what stops a 3-stage chain from finishing a
            # request nobody is waiting for
            return self._deadline_response(
                tin, session_id, stage, "post-compute"
            )
        next_env = {
            "task_id": task_id,
            "session_id": session_id,
            "stage": stage + 1,
            "payload": result,
        }
        if start_pos == 0:
            # multi-tenant LoRA: the session->adapter binding happens at
            # EVERY stage's admission, so the first chunk's `adapter` key
            # rides the relay — each downstream stage binds its own slice
            ad = (env.get("payload") or {}).get("adapter")
            if ad is not None:
                result["adapter"] = ad
        if "route" in env:
            next_env["route"] = env["route"]
        if deadline_ms is not None:
            next_env[retrylib.DEADLINE_KEY] = deadline_ms
        try:
            t1 = time.perf_counter()
            resp = await self._relay(next_env, stage + 1, tin=tin)
            self.metrics.observe("hop.relay_ms", (time.perf_counter() - t1) * 1e3)
            return resp
        except NoNodeForStage as e:
            return self._error_response(503, f"no next node: {e}")

    def _maybe_oom_event(
        self, e: BaseException, tin: Optional[tracelib.SpanContext],
        stage: int,
    ) -> None:
        """Journal a device OOM when a compute failure smells like one
        (XLA raises RESOURCE_EXHAUSTED RuntimeErrors) — the single most
        postmortem-relevant failure a TPU node produces."""
        msg = str(e)
        if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
            self.journal.emit(
                "oom", trace=tin, stage=stage,
                error=f"{type(e).__name__}: {msg}"[:200],
            )

    def _deadline_response(
        self, tin: Optional[tracelib.SpanContext], session_id: Optional[str],
        stage: int, where: str,
    ) -> web.Response:
        """The typed deadline failure: 408 + code "deadline" (non-
        retryable under the client's ServerError contract — the budget is
        a property of the REQUEST, not of any replica, so another attempt
        cannot succeed either), journaled so postmortems can tell
        "overloaded and shedding correctly" from "failing"."""
        self.metrics.inc("deadline.expired")
        self.journal.emit(
            "deadline.exceeded", trace=tin, session=session_id, stage=stage,
            where=where,
        )
        return self._error_response(
            408, f"deadline exceeded ({where})", code="deadline"
        )

    def _admission_shed(self):
        """(code, message) when NEW sessions must be shed, else None:
        "draining" after POST /drain, "busy" when the paged-KV block pool
        is below its free-watermark reserve (admission_reserve x pool;
        ROADMAP 2d — a pool-backed node's real capacity is blocks_free,
        not lane count)."""
        if self._draining:
            return (
                "draining",
                "node is draining: not admitting new sessions",
            )
        low = self._pool_under_reserve()
        if low is not None:
            free, total, reserve = low
            return (
                "busy",
                f"KV block pool low: {free} free of {total} "
                f"(admission reserve {reserve})",
            )
        return None

    def _pool_under_reserve(self):
        """(free, total, reserve) when the paged block pool is below its
        admission watermark, else None — shared by the admission shed
        above and the gossiped `shed` flag (routers suppress the
        cache-affinity bonus and penalize affinity-scored picks on a
        shedding replica: obs.canary.under_admission_watermark)."""
        pool = getattr(self.executor, "pool", None)
        if pool is None:
            return None
        try:
            total = int(pool.num_blocks)
            free = int(pool.blocks_free)
        except Exception:
            return None  # duck-typed executor without pool counters
        reserve = max(1, int(self.admission_reserve * total))
        if free < reserve:
            return (free, total, reserve)
        return None

    def _prefix_digest(self) -> Optional[Dict[str, Any]]:
        """The executor's gossip-ready prefix digest (`pfx` field), or
        None (key omitted): which prompt prefixes this replica already
        holds as KV blocks, truncated-key form (core.prefix.make_digest).
        Entry routers score new sessions' prompts against it
        (control.path_finder / control.dstar cache-affinity bonus)."""
        fn = getattr(self.executor, "prefix_digest", None)
        if not callable(fn):
            return None
        try:
            return fn()
        except Exception:
            log.debug("prefix digest unavailable", exc_info=True)
            return None

    def _adapter_digest(self):
        """Resident non-base adapter names (bounded — runtime/adapters
        ADA_GOSSIP_MAX), or None (key omitted): which tenants' adapters
        this replica already holds device-resident. Entry routers score
        new sessions' `adapter` against it (AdapterAffinity — the same
        bounded bonus seam as the `pfx` digest); a miss is a HOT-LOAD on
        the landing replica, never a reject. A registry with NOTHING
        resident announces `[]`, not omission: key PRESENCE is the
        capability marker tenant-session handoff/standby target picks
        require, so an adapter-stamped payload is never offered to an
        old-release or registry-less peer that would silently adopt it
        onto the base weights."""
        reg = getattr(self.executor, "adapters", None)
        if reg is None:
            return None
        try:
            return reg.resident_names()
        except Exception:
            log.debug("adapter digest unavailable", exc_info=True)
            return None

    def _cachehit_frac(self) -> Optional[float]:
        """Trailing-window prefix-cache hit rate: tokens the pool served
        from cached blocks over all prompt tokens admitted (hits +
        actually-prefilled), from the windowed kv.prefix_* counters the
        devtel refresh mirrors (obs.tsdb). None — key omitted — when the
        window saw no prompt traffic or the series don't exist (dense
        executors, events disabled): stale ratios must age out with the
        window, never freeze."""
        h = self.tsdb.history()
        hit = tsdblib.trailing_sum(h, "kv.prefix_hit_tokens")
        pre = tsdblib.trailing_sum(h, "kv.prefill_tokens")
        if hit is None or pre is None:
            return None
        denom = hit + pre
        if denom <= 0:
            return None
        return round(hit / denom, 4)

    def _retry_after_s(self) -> float:
        """Retry-After hint for shed responses, derived from window
        occupancy: roughly one arrival window per unit of queue pressure
        (inflight/cap), floored at 50 ms and capped at 5 s so a burst of
        shed clients smears itself across a few windows instead of
        re-arriving as one synchronized wave."""
        inflight = self.scheduler.inflight if hasattr(self, "scheduler") else 0
        cap = max(1, self.info.capacity)
        base = max(self.window_ms / 1e3, 0.05)
        return round(min(5.0, base * (1.0 + inflight / cap)), 3)

    def _holds_session(self, session_id: str) -> bool:
        store = getattr(self.executor, "sessions", None)
        try:
            return store is not None and session_id in store
        except TypeError:
            return False

    def _gossip_session_holder(
        self, session_id: str, stage: int, exclude=None
    ) -> Optional[str]:
        """node_id of a live same-stage replica advertising this session's
        KV in its gossip record (see _advertised_sessions), or None."""
        h = sess_hash(session_id)
        for nid, value in self.dht.get_stage(stage).items():
            if exclude and nid in exclude:
                continue
            if h in (value.get("sess") or ()):
                return nid
        return None

    def _gossip_standby_holder(
        self, session_id: str, stage: int, exclude=None
    ) -> Optional[str]:
        """node_id of a live same-stage replica advertising this
        session's REPLICATED prefix (`standby` gossip field — async KV
        replication, runtime/repl), or None. Consulted only after the
        `sess` lookup comes up empty: a live authoritative holder always
        beats a lagging shadow."""
        h = sess_hash(session_id)
        for nid, value in self.dht.get_stage(stage).items():
            if exclude and nid in exclude:
                continue
            if h in (value.get("standby") or ()):
                return nid
        return None

    def _standby_len(
        self, session_id: str, stage: Optional[int] = None
    ) -> Optional[int]:
        """Replicated frontier of a locally held shadow session, or None
        (replication off / session unknown here / — with `stage` — the
        shadow belongs to a DIFFERENT stage, e.g. one this node served
        before a migration: promotion could never use it, so the rescue
        loop must not short-circuit on it either)."""
        if self.standby is None:
            return None
        if stage is not None and self.standby.stage_of(session_id) != stage:
            return None
        return self.standby.length(session_id)

    def _promote_standby_sync(self, session_id: str) -> bool:
        """Worker thread: import the accumulated shadow KV into the
        executor through the ordinary handoff path — the fail-closed
        validator (runtime/handoff.decode) is the promotion gate, so a
        corrupt or wrong-layout shadow rejects cleanly instead of
        corrupting a lane."""
        assert self.standby is not None
        payload = self.standby.payload(session_id)
        if payload is None:
            return False
        imp = getattr(self.executor, "import_session", None)
        if imp is None:
            return False
        try:
            return bool(imp(session_id, payload))
        except Exception:
            log.exception("standby promotion import failed")
            return False

    async def _promote_or_offer(
        self, session_id: str, stage: int, start_pos: int,
        tin: Optional[tracelib.SpanContext],
    ) -> Optional[web.Response]:
        """Resolve a KV-less mid-session chunk against the local
        StandbyStore. Returns a Response to send (the typed resume
        offer), or None — either the shadow was promoted (the caller
        serves the chunk against the now-resident session) or there is
        nothing usable here (the caller degrades to the ordinary
        409/restart path)."""
        if self.standby is None:
            return None
        F = self.standby.length(session_id)
        if F is None or F <= 0 or self.standby.stage_of(session_id) != stage:
            return None
        if start_pos > F:
            # promotion OFFER: we hold the replicated prefix up to F.
            # The 409 keeps code "session_state" (old clients restart
            # fully — exactly today's degraded path) and adds
            # `resume_from`: new clients re-send only [F, start_pos) —
            # the re-prefill is bounded by the replication lag.
            if eventslib.enabled():
                self.metrics.inc("repl.offers")
                self.metrics.inc("repl.tail_tokens", start_pos - F)
            self.journal.emit(
                "standby.offer", trace=tin, session=session_id,
                stage=stage, frontier=F, chunk_start=start_pos,
            )
            return self._error_response(
                409,
                f"session {session_id}: standby KV reaches {F} < chunk "
                f"start {start_pos} — resume from {F}",
                code="session_state", resume_from=F,
            )
        ok = await self.scheduler.run(self._promote_standby_sync, session_id)
        if ok:
            self.standby.drop(session_id)
            if eventslib.enabled():
                self.metrics.inc("repl.promotions")
                self.metrics.inc("repl.resumed_tokens", F)
            self.journal.emit(
                "standby.promote", trace=tin, session=session_id,
                stage=stage, frontier=F, chunk_start=start_pos,
            )
            # advertise the promoted session NOW (`sess`): the failed-
            # over client's next chunks route straight here, mirroring
            # handle_import_session's adopt-then-announce
            self.announce()
            return None  # resident now: the caller serves the chunk
        # import declined — which covers BOTH a validation failure and a
        # transient capacity miss (no free lane / pool blocks during the
        # mass-failover spike a crash creates; import_session folds both
        # into False). KEEP the shadow: a capacity miss may promote fine
        # on the client's very next resume retry, and a truly corrupt
        # shadow is abandoned when the client restarts under a fresh
        # session id (the TTL sweep collects it). Dropping here would
        # convert a momentary full pool into a permanent full restart.
        if eventslib.enabled():
            self.metrics.inc("repl.stale")
        self.journal.emit(
            "standby.stale", trace=tin, session=session_id, stage=stage,
            frontier=F,
        )
        return None  # degrade: ordinary 409 -> client restart

    # ------------------------------------------ standby replication (primary)

    def _repl_candidates(self):
        """Ranked same-stage standby candidates for the replicator —
        path_finder.ranked_nodes ordering (outlier-penalized, draining-
        excluded), minus this node (anti-affinity: the standby must
        survive the primary's crash) and peers cooling down after a
        failed/declined ship."""
        from inferd_tpu.control.path_finder import ranked_nodes

        now = time.monotonic()
        self._repl_peer_cooldown = {
            nid: t for nid, t in self._repl_peer_cooldown.items() if t > now
        }
        exclude = {self.info.node_id, *self._repl_peer_cooldown}
        stage_map = self.dht.get_stage(self.info.stage)
        cands = ranked_nodes(stage_map, exclude=exclude)
        if not cands and len(stage_map) > 1:
            # every peer is cooling down: better a recently flaky standby
            # than none (the cooldown bounds RETRY RATE, not recovery)
            cands = ranked_nodes(stage_map, exclude={self.info.node_id})
        return cands

    async def _repl_loop(self) -> None:
        """Replication tick: ship newly completed KV past each resident
        session's frontier to its sticky standby (runtime/repl). Purely
        additive and best-effort — a failed ship costs nothing but RPO."""
        while True:
            await asyncio.sleep(self.repl_interval_s)
            try:
                await self._repl_tick()
            except Exception:
                log.exception("standby replication tick failed")

    async def _repl_tick(self) -> None:
        assert self.replicator is not None
        ex = self.executor
        lengths_fn = getattr(ex, "session_lengths", None)
        delta_fn = getattr(ex, "export_session_delta", None)
        if (
            not callable(lengths_fn) or not callable(delta_fn)
            or self._http is None or self._draining
        ):
            return
        loop = asyncio.get_running_loop()
        lengths = await loop.run_in_executor(None, lengths_fn)
        # silent forget for sessions that merely lost residency (LRU
        # lane eviction, live handoff): their standby shadows STAY — a
        # continuing stream promotes off them. Explicit client ends send
        # a drop notice from handle_end_session instead.
        self.replicator.prune(lengths)
        if eventslib.enabled():
            self.metrics.set_gauge(
                "repl.lag_tokens", float(self.replicator.lag_tokens(lengths))
            )
        def ship_failed(sid: str, standby: str, count_error: bool) -> None:
            # one definition of "this standby didn't take the delta":
            # forget the sticky pick (re-pick next tick, re-ship from 0)
            # and cool the peer down so a dead/declining one isn't
            # re-tried every tick
            self.replicator.note_standby_dead(sid)
            self._repl_peer_cooldown[standby] = (
                time.monotonic() + self.peer_cooldown_s
            )
            if count_error and eventslib.enabled():
                self.metrics.inc("repl.ship_errors")

        ad_fn = getattr(ex, "session_adapters", None)
        ad_map = ad_fn() if callable(ad_fn) else None
        for sid, standby, frontier in self.replicator.plan(lengths, ad_map):
            rec = self.dht.get_stage(self.info.stage).get(standby)
            if rec is None:
                self.replicator.note_standby_dead(sid)
                continue
            delta = await loop.run_in_executor(None, delta_fn, sid, frontier)
            if delta is None:
                continue  # e.g. paged: no full block completed yet
            body = wire.pack({
                "session_id": sid, "stage": self.info.stage, **delta,
            })
            try:
                host, port = node_addr(rec)
                async with self._http.post(
                    f"http://{host}:{port}{REPLICATE_SESSION_PATH}",
                    data=body,
                ) as r:
                    resp = (
                        wire.unpack(await r.read()) if r.status == 200
                        else None
                    )
            except (OSError, asyncio.TimeoutError, aiohttp.ClientError):
                ship_failed(sid, standby, count_error=True)
                continue
            if not isinstance(resp, dict):
                # non-200 (e.g. the peer runs without --standby-repl) or
                # garbage: cool the peer down and re-pick next tick
                ship_failed(sid, standby, count_error=True)
                continue
            ok = bool(resp.get("ok"))
            if not ok and (resp.get("serving") or resp.get("unservable")):
                # the "standby" actually SERVES this session (a drain
                # adopted it there), or it can never promote this
                # tenant's adapter (no registry / name outside its
                # catalog): stop shadowing, cool it down, re-pick next
                # tick — not a ship error, a mis-pick
                ship_failed(sid, standby, count_error=False)
                continue
            peer_len = resp.get("length") if ok else resp.get("have")
            self.replicator.record(sid, standby, ok, peer_len, len(body))
            if eventslib.enabled():
                if ok:
                    self.metrics.inc("repl.bytes", len(body))
                    self.metrics.inc("repl.ships")
                    if frontier == 0:
                        # journal the session's arrival on its standby
                        # once per (session, standby) sync, not per tick
                        self.journal.emit(
                            "session.replicated", session=sid,
                            standby=standby,
                            **{"length": int(peer_len or 0)},
                        )
                else:
                    self.metrics.inc("repl.ship_declined")

    async def _send_standby_drop(self, session_id: str, standby: str) -> None:
        """Best-effort drop notice to an ended session's sticky standby
        (the standby's TTL sweep is the backstop when this never lands)."""
        rec = self.dht.get_stage(self.info.stage).get(standby)
        if rec is None or self._http is None:
            return
        try:
            host, port = node_addr(rec)
            async with self._http.post(
                f"http://{host}:{port}{REPLICATE_SESSION_PATH}",
                data=wire.pack({
                    "session_id": session_id, "stage": self.info.stage,
                    "drop": True,
                }),
            ):
                pass
        except (OSError, asyncio.TimeoutError, aiohttp.ClientError):
            pass

    async def handle_replicate_session(
        self, request: web.Request
    ) -> web.Response:
        """Accept one async-replication delta into the StandbyStore
        (host-side shadow KV — no lane, no device state until
        promotion). POST {"session_id", "stage", "start", handoff
        payload} -> {"ok": true, "length": L} or {"ok": false, "have":
        H} (the primary re-syncs from H). 501 with --standby-repl off —
        a replication-blind node must say so, not silently eat bytes."""
        if self.standby is None:
            return self._error_response(
                501,
                "standby replication disabled (start with --standby-repl)",
                code="repl_off",
            )
        try:
            env = wire.unpack(await request.read())
            session_id = env["session_id"]
            stage = int(env["stage"])
        except Exception as e:
            return self._error_response(400, f"bad replicate_session: {e}")
        if stage != self.info.stage:
            return self._error_response(
                409,
                f"wrong stage: this node serves {self.info.stage}, not {stage}",
                code="wrong_stage",
            )
        if env.get("drop"):
            # the primary's session ended: free the shadow (and its
            # `standby` advert) now instead of waiting out the TTL
            had = session_id in self.standby
            self.standby.drop(session_id)
            if had:
                self.announce(urgent=False)
            return web.Response(body=wire.pack({"ok": True, "length": 0}))
        if self._holds_session(session_id):
            # we SERVE this session (e.g. adopted it via drain handoff):
            # shadowing ourselves is meaningless — tell the primary to
            # pick another standby
            return web.Response(body=wire.pack(
                {"ok": False, "have": 0, "serving": True}
            ))
        from inferd_tpu.runtime.adapters import registry_can_serve

        if not registry_can_serve(self.executor, env.get("adapter")):
            # a tenant delta this replica can NEVER promote (no
            # registry, or the name is outside our catalog): declining
            # NOW makes the primary re-pick instead of streaming
            # shadows toward a guaranteed promotion decline — a
            # bounded-RPO promise that was silently void
            if eventslib.enabled():
                self.metrics.inc("repl.recv_declined")
            return web.Response(body=wire.pack(
                {"ok": False, "have": 0, "unservable": True}
            ))
        had = session_id in self.standby
        ok, have = await asyncio.get_running_loop().run_in_executor(
            None, self.standby.apply, session_id, stage, env
        )
        if eventslib.enabled():
            self.metrics.inc("repl.recv" if ok else "repl.recv_declined")
        if ok and not had:
            # the `standby` advert must reach peers before the primary
            # dies for the rescue path to find us — non-urgent: the 1 s
            # gossip loop carries it well inside the record TTL
            self.announce(urgent=False)
        body = {"ok": ok, "length": have} if ok else {"ok": False, "have": have}
        return web.Response(body=wire.pack(body))

    def _timed_process(self, executor, session_id: str, payload: Dict[str, Any]):
        """Executor call + its pure compute time in ms and wall-clock
        start/end stamps (runs in the worker thread, so the measurement
        excludes the pool's queue wait; the wall stamps become the
        compute span and bound the queue span). The executor is passed
        in, bound at request entry — see handle_forward's migration-race
        note."""
        w0 = tracelib.now()
        t = time.perf_counter()
        result = executor.process(session_id, payload)
        pure_ms = (time.perf_counter() - t) * 1e3
        return result, pure_ms, w0, w0 + pure_ms / 1e3

    def _is_final(self, result: Dict[str, Any]) -> bool:
        # "tokens": a multi-step fused decode result (single-stage
        # topologies only — already sampled on device, nothing to relay)
        return (
            "logits" in result or "tokens" in result
            or "result_for_user" in result
        )

    # ------------------------------------------ stage-window flush + relay

    def _run_stage_window(self, executor, entries) -> None:
        """WindowedBatcher flush callback (worker thread, no locks held):
        ONE co-batched device step for every co-arrived decode entry, then
        ONE relay per next-hop group instead of one per session.

        Entry payloads are (session_id, env, tin, t_enqueue). Per-entry
        failures set entry.error (one stale session must not fail its
        co-batch); entries that need no relay resolve to ("local", result)
        and the handler coroutine shapes the response; relayed entries
        resolve to ("relayed", status, body) with the downstream reply.
        The relay runs on the event loop while THIS worker thread blocks —
        the batcher has already reset its flusher slot, so the next
        window's compute overlaps this window's downstream send."""
        w0 = tracelib.now()
        t0 = time.perf_counter()
        items = [
            (e.payload[0], (e.payload[1].get("payload") or {}))
            for e in entries
        ]
        drained: list = []
        # window end / compute start stamp: set at DRAIN time (after the
        # device lock was acquired), not at flush entry — drain-absorbed
        # entries were enqueued while the previous step held the device,
        # so stamping w0 would give their window spans negative durations
        marks = {"drain": w0}

        def drain():
            """Continuous batching: once the executor holds the device,
            absorb the entries that arrived while the PREVIOUS step was
            running (otherwise arrival phase, not load, sets the batch
            size). We own the drained entries: results AND events are
            ours to deliver (window.drain_pending contract)."""
            extra = executor.window.drain_pending()
            marks["drain"] = tracelib.now()
            drained.extend(extra)
            return [
                (e.payload[0], (e.payload[1].get("payload") or {}))
                for e in extra
            ]

        try:
            outs = executor.process_batch(items, drain=drain)
            entries = list(entries) + drained
        except Exception as exc:
            # process_batch failed wholesale: the flush loop propagates to
            # ITS entries, but the drained ones are ours to fail + release
            for e in drained:
                e.error = exc
                e.event.set()
            raise
        pure_ms = (time.perf_counter() - t0) * 1e3
        w1 = tracelib.now()
        n_live = sum(1 for o in outs if not isinstance(o, Exception))
        # token-true accounting: a multi-step fused decode entry commits
        # K tokens in this one dispatch (its result carries them under
        # "tokens"); counting 1 would understate /metrics tok/s and the
        # `obs merge` per-token breakdowns by K
        n_tok = sum(
            len(o["tokens"][0]) if isinstance(o, dict) and "tokens" in o else 1
            for o in outs if not isinstance(o, Exception)
        )
        if n_live:
            self.metrics.observe("stage.compute_ms", pure_ms)
            if eventslib.enabled():
                # token-true per-stage throughput counter (see the
                # non-window sibling in _forward_inner)
                self.metrics.inc("stage.tokens", n_tok)
            # co-batch-size histogram (in TOKENS per device step): the
            # mechanism's whole value proposition, observable at /metrics
            # and in `perf check`
            self.metrics.observe(
                "window.cobatch", n_tok,
                # tokens per dispatch now reaches lanes x K (e.g. 8 lanes
                # at K=16 = 128): bounds extend past the old lane-count
                # domain so K-step windows keep histogram resolution
                bounds_ms=[1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024],
            )
            self._svc_ewma = (
                pure_ms if self._svc_ewma is None
                else 0.8 * self._svc_ewma + 0.2 * pure_ms
            )
        relays = []
        traced = tracelib.enabled()
        try:
            self._distribute_window(entries, outs, relays, marks["drain"],
                                    w1, pure_ms, n_live, traced, n_tok)
        finally:
            # the flush loop signals only its OWN entries; drained ones
            # release here, after their results/errors landed
            for e in drained:
                if e.error is None and e.result is None:
                    e.error = RuntimeError("window flush dropped an entry")
                e.event.set()

    def _distribute_window(self, entries, outs, relays, t_drain, w1,
                           pure_ms, n_live, traced, n_tok=None) -> None:
        if n_tok is None:
            n_tok = n_live
        for e, out in zip(entries, outs):
            _sid, env, tin, t_q = e.payload
            stage_attr = int(env.get("stage", -1) or -1)
            if tin is not None and traced:
                # `window` phase: enqueue -> batch formation (the
                # co-batching wait this PR introduces — merge CLI
                # breakdowns show it next to queue/compute); clamped in
                # case an entry slipped in between drain and stamp. Then
                # the shared batched step from the drain point. `tokens`
                # counts real committed tokens (K per multi-step entry) so
                # per-token breakdowns divide by the truth.
                self.tracer.record_span(
                    "window", "window", t_q, max(t_q, t_drain), parent=tin,
                    attrs={"stage": stage_attr, "cobatch": n_live,
                           "tokens": n_tok},
                )
                self.tracer.record_span(
                    "compute", "compute", max(t_q, t_drain), w1, parent=tin,
                    attrs={"stage": stage_attr, "ms": round(pure_ms, 3),
                           "cobatch": n_live, "tokens": n_tok},
                )
            if isinstance(out, Exception):
                e.error = out
                continue
            if self._is_final(out) or not env.get("relay", True):
                e.result = ("local", out)
            else:
                relays.append((e, env, out))
        if not relays:
            return
        if self._loop is None or self._loop.is_closed():
            err = RuntimeError("node event loop unavailable for relay")
            for e, _env, _out in relays:
                e.error = err
            return
        # block THIS worker thread on the loop-side relay; entries release
        # when their downstream replies land
        asyncio.run_coroutine_threadsafe(
            self._relay_window(relays), self._loop
        ).result(timeout=self.hop_timeout_s * 2 + 30)

    async def _relay_window(self, relays) -> None:
        """Coalesced relay of one flushed window (event loop). Groups the
        window's entries by their picked next hop; a group of one takes
        the ordinary single-session relay, a larger group ships ONE
        wire.coalesce_forward envelope (N HTTP hops -> 1). Sets each
        entry's result/error; never raises."""
        groups: "OrderedDict[str, tuple]" = OrderedDict()
        for e, env, result in relays:
            stage = int(env.get("stage", 0)) + 1
            next_env = {
                "task_id": env.get("task_id"),
                "session_id": env.get("session_id"),
                "stage": stage,
                "payload": result,
            }
            if "route" in env:
                next_env["route"] = env["route"]
            if retrylib.DEADLINE_KEY in env:
                # the deadline follows the session's work downstream —
                # coalesced frames carry it per session (split_forward
                # reconstructs it on the receiver)
                next_env[retrylib.DEADLINE_KEY] = env[retrylib.DEADLINE_KEY]
            try:
                nid, value = await self._pick_next(
                    env.get("session_id"), stage, route=env.get("route")
                )
            except NoNodeForStage as exc:
                e.result = (
                    "relayed", 503,
                    wire.pack({"error": f"no next node: {exc}"}),
                )
                continue
            except Exception as exc:
                e.error = exc
                continue
            if nid not in groups:
                groups[nid] = (value, [])
            groups[nid][1].append((e, next_env))
        # groups relay CONCURRENTLY: when affinity splits a window over
        # several next hops, total relay time is the max downstream RTT,
        # not the sum (and the flusher's completion timeout stays a
        # per-hop bound, never a per-window one)
        await asyncio.gather(*(
            self._relay_entry_single(*members[0]) if len(members) == 1
            else self._relay_group(nid, value, members)
            for nid, (value, members) in groups.items()
        ))

    async def _relay_entry_single(self, e, next_env) -> None:
        """One windowed entry's ordinary single-session relay (identical
        bytes to the pre-window path — what keeps old nodes decodable)."""
        tin = e.payload[2]
        try:
            resp = await self._relay(next_env, next_env["stage"], tin=tin)
            e.result = ("relayed", resp.status, bytes(resp.body or b""))
        except NoNodeForStage as exc:
            e.result = (
                "relayed", 503, wire.pack({"error": f"no next node: {exc}"})
            )
        except Exception as exc:
            e.error = exc

    async def _relay_group(self, nid, value, members) -> None:
        """ONE coalesced envelope for a same-next-hop group. Any failure
        (transport, an old peer rejecting the multi form, a malformed
        reply) falls back to per-session relays — coalescing is an
        optimization, never a new failure mode."""
        traced = tracelib.enabled()
        envs, spans = [], []
        for e, next_env in members:
            tin = e.payload[2]
            rctx = None
            if tin is not None and traced:
                rctx = tracelib.SpanContext(tin.trace_id, tracelib.new_id())
                next_env = {**next_env, tracelib.WIRE_KEY: rctx.to_wire()}
            envs.append(next_env)
            spans.append((tin, rctx))
        stage = envs[0]["stage"]
        t_wall = tracelib.now()
        try:
            body = wire.pack(wire.coalesce_forward(envs))
            self.metrics.inc("hop.bytes_total", len(body))
            self.metrics.inc("hop.count")
            self.metrics.inc("hop.coalesced")
            self.metrics.inc("hop.coalesced_sessions", len(members))
            host, port = node_addr(value)
            assert self._http is not None
            async with self._http.post(
                f"http://{host}:{port}{FORWARD_PATH}", data=body
            ) as r:
                raw = await r.read()
                if r.status != 200:
                    raise RuntimeError(
                        f"multi relay to {nid} answered {r.status}"
                    )
            reply = wire.unpack(raw)
            frames = (
                reply.get(wire.MULTI_KEY) if isinstance(reply, dict) else None
            )
            if not isinstance(frames, list) or len(frames) != len(members):
                raise RuntimeError(f"bad multi reply from {nid}")
            for (e, _ne), fr in zip(members, frames):
                e.result = (
                    "relayed",
                    int(fr.get("status", 500)),
                    bytes(fr.get("body") or b""),
                )
        except Exception as exc:
            # per-session fallback: an old node that cannot decode the
            # multi envelope (or a dead hop) degrades to N single relays,
            # each with its own re-pick/502 handling
            log.warning(
                "coalesced relay to %s failed (%s); per-session fallback",
                nid, exc,
            )
            self.metrics.inc("hop.coalesced_fallback")
            self.journal.emit(
                "relay.coalesced_fallback", peer=nid, stage=stage,
                sessions=len(members),
                error=f"{type(exc).__name__}: {exc}"[:120],
            )
            for _e, next_env in members:
                next_env.pop(tracelib.WIRE_KEY, None)  # _relay re-stamps
            # concurrent, like the pre-coalescing path: N sequential
            # fallback relays would turn one slow peer into sum-of-RTTs
            await asyncio.gather(*(
                self._relay_entry_single(e, next_env)
                for e, next_env in members
            ))
        finally:
            if traced:
                t1 = tracelib.now()
                for tin, rctx in spans:
                    if rctx is not None:
                        self.tracer.record_span(
                            "relay", "relay", t_wall, t1, parent=tin,
                            ctx=rctx,
                            attrs={"stage": stage,
                                   "coalesced": len(members)},
                        )

    def _plan_route(
        self, start_stage: int, affinity=None,
    ) -> Optional[Dict[str, str]]:
        """Whole-chain route {str(stage): node_id} for stages start_stage..
        last, from PathFinder.find_best_chain (the long-lived incremental
        D*-Lite planner). `affinity` (e.g. the session's AdapterAffinity)
        re-ranks the chain's FIRST stage by the bounded affinity bonus —
        dstar.node_cost composition: suppressed on shedding/draining,
        dominated by the outlier penalty. Returns None when no complete
        chain exists (caller degrades to per-hop picks)."""
        try:
            chain = self.path_finder.find_best_chain(
                start_stage, affinity=affinity
            )
        except NoNodeForStage:
            self.metrics.inc("route.plan_failed")
            return None
        except Exception:
            log.exception("chain planning failed; per-hop fallback")
            self.metrics.inc("route.plan_failed")
            return None
        self.metrics.inc("route.planned")
        return {
            str(s): nid
            for s, (nid, _) in enumerate(chain, start=start_stage)
        }

    async def _pick_next(
        self, session_id: Optional[str], stage: int, exclude=None, route=None,
        prefer: Optional[str] = None,
    ):
        """Next-replica pick. `prefer` (a node_id the caller already
        verified, e.g. the rescue path's gossip holder) wins outright when
        live and not excluded. Otherwise, in priority order: (1) local
        session affinity
        — the replica this node already routed the session to; (2) the
        swarm-shared session location — a replica ADVERTISING the session's
        KV in its gossip record (rescues sessions whose affinity map died
        with another node); (3) the planned D*-Lite route riding the
        envelope (new sessions); (4) min-load pick."""
        key = (session_id, stage) if session_id else None
        if prefer is not None and (not exclude or prefer not in exclude):
            value = self.dht.get_stage(stage).get(prefer)
            if value is not None:
                if key is not None:
                    self._session_next[key] = (prefer, time.monotonic())
                    self._session_next.move_to_end(key)
                return prefer, value
        if key is not None and key in self._session_next:
            nid, _ = self._session_next[key]
            value = self.dht.get_stage(stage).get(nid)
            if value is not None and (not exclude or nid not in exclude):
                self._session_next[key] = (nid, time.monotonic())
                self._session_next.move_to_end(key)
                return nid, value
            # the remembered replica is gone; its KV is lost — fall through
            # to a fresh pick (the executor there will reject mid-session
            # chunks and the client restarts the session)
            self._session_next.pop(key, None)
        if session_id is not None:
            nid = self._gossip_session_holder(session_id, stage, exclude)
            if nid is not None:
                value = self.dht.get_stage(stage).get(nid)
                if value is not None:
                    self.metrics.inc("route.sess_gossip")
                    self._session_next[key] = (nid, time.monotonic())
                    self._session_next.move_to_end(key)
                    while len(self._session_next) > self._session_next_cap:
                        self._session_next.popitem(last=False)
                    return nid, value
        if route:
            nid = route.get(str(stage))
            if nid and (not exclude or nid not in exclude):
                value = self.dht.get_stage(stage).get(nid)
                if value is not None:
                    self.metrics.inc("route.followed")
                    if key is not None:
                        self._session_next[key] = (nid, time.monotonic())
                        self._session_next.move_to_end(key)
                        while len(self._session_next) > self._session_next_cap:
                            self._session_next.popitem(last=False)
                    return nid, value
            # planned replica died between planning and arrival: fall
            # through to the fresh pick (and let affinity re-pin)
            self.metrics.inc("route.stale")
        nid, value = await self.path_finder.find_best_node(
            stage, exclude=self._with_cooldown(stage, exclude)
        )
        if key is not None:
            self._session_next[key] = (nid, time.monotonic())
            self._session_next.move_to_end(key)
            while len(self._session_next) > self._session_next_cap:
                self._session_next.popitem(last=False)
        return nid, value

    def _with_cooldown(self, stage: int, exclude):
        """Exclude-set for the FRESH min-load pick, augmented with peers
        still inside their dead-peer cooldown (_note_peer_failure) —
        unless that would leave the stage with no candidate at all
        (availability beats steering). Affinity/holder/route picks never
        consult this: a session's KV location is correctness, not a
        steering preference."""
        now = time.monotonic()
        if self._peer_cooldown:
            self._peer_cooldown = {
                k: t for k, t in self._peer_cooldown.items() if t > now
            }
        base = set(exclude or ())
        cooling = set(self._peer_cooldown) - base
        if not cooling:
            return exclude
        alive = set(self.dht.get_stage(stage)) - base
        if alive - cooling:
            return base | cooling
        return exclude

    def _note_peer_failure(self, node_id: str) -> None:
        """Start (or extend) a replica's dead-peer cooldown after a
        transport-dead or 5xx-answering relay: fresh picks steer around
        it for peer_cooldown_s instead of rediscovering the failure once
        per new session — the routing half of overload containment (a
        stalling replica otherwise keeps collecting half a stage's
        admissions at one hop-timeout each)."""
        self._peer_cooldown[node_id] = (
            time.monotonic() + self.peer_cooldown_s
        )
        self.metrics.inc("peer.cooldown")
        # the CHAIN planner folds the death in immediately (INF in-edges,
        # incremental D*-Lite compute + its own resurrect-proof cooldown)
        # instead of replanning sessions into the corpse until its gossip
        # record TTLs out (control.path_finder.note_peer_dead)
        self.path_finder.note_peer_dead(node_id)

    async def _relay(
        self, env: Dict[str, Any], stage: int, exclude=None,
        prefer: Optional[str] = None,
        tin: Optional[tracelib.SpanContext] = None, phase: str = "relay",
        span_attrs: Optional[Dict[str, Any]] = None,
        attempts: int = 2,
    ) -> web.Response:
        """Relay to the picked next node; on a dead hop (its DHT record
        hasn't TTL'd out yet), re-pick once excluding it, then surface a
        wire-packed 502 — never an unhandled exception (aiohttp would turn
        that into a bare HTML 500 the client can't parse).

        When `tin` (this node's server span) is set and tracing is on, the
        hop records a `phase` span ("relay", or "rescue" from the rescue
        path) whose id rides the forwarded envelope's `trace` key — its
        send/recv interval brackets the remote node's spans, which is the
        anchor pair the merge CLI corrects clock skew with.

        Overload plane: the per-hop HTTP timeout is the REMAINING
        end-to-end budget when a `deadline_ms` rides the envelope
        (clamped by hop_timeout_s) — a stalled peer costs at most what
        the request had left, never a full static timeout. Idempotent
        single-token decode relays may HEDGE: after an adaptive delay
        (trailing hop p95, or hedge_delay_ms when pinned) the same
        envelope fires at a second replica and the first 200 wins, the
        loser is cancelled — under the <=5% hedge_budget (see
        _relay_exchange)."""
        assert self._http is not None
        exclude = set(exclude or ())
        session_id = env.get("session_id")
        deadline_ms = env.get(retrylib.DEADLINE_KEY)
        # hedging only on the plain relay path: the rescue path already
        # targets a verified holder, and a mismatch re-route is rare
        # enough that a second copy buys nothing
        may_hedge = (
            phase == "relay" and prefer is None
            and self.hedge_mode != "off"
            and _is_decode_step(env.get("payload"))
        )
        relay_ctx: Optional[tracelib.SpanContext] = None
        t_wall = 0.0
        if tin is not None and tracelib.enabled():
            relay_ctx = tracelib.SpanContext(tin.trace_id, tracelib.new_id())
            env = {**env, tracelib.WIRE_KEY: relay_ctx.to_wire()}
            t_wall = tracelib.now()
        body = wire.pack(env)  # pack once: env carries multi-MB activations
        # bytes-per-hop visibility (/stats): avg = bytes_total / count
        self.metrics.inc("hop.bytes_total", len(body))
        self.metrics.inc("hop.count")
        self.hedge_budget.note()  # one primary send (the <=5% denominator)
        last_err: Optional[Exception] = None
        try:
            # attempts=1 (the rescue path): the caller targets ONE
            # verified holder and runs its own bounded bounce loop — the
            # blind re-pick here would only spin the empty-stage recovery
            # hook (adopt + retry sleeps) once per bounce
            for attempt in range(attempts):
                node_id, value = await self._pick_next(
                    session_id, stage, exclude, route=env.get("route"),
                    prefer=prefer if attempt == 0 else None,
                )
                rem = retrylib.remaining_s(deadline_ms)
                if rem is not None and rem <= 0:
                    return self._deadline_response(
                        tin, session_id, stage, "relay"
                    )
                timeout_s = (
                    self.hop_timeout_s if rem is None
                    # +50 ms so the downstream node's own typed 408 wins
                    # the race against our transport timeout
                    else min(self.hop_timeout_s, rem + 0.05)
                )
                try:
                    status, raw = await self._relay_exchange(
                        body, stage, node_id, value, timeout_s,
                        session_id=session_id, exclude=exclude,
                        allow_hedge=(may_hedge and attempt == 0), tin=tin,
                    )
                    if status >= 500 and status != 503:
                        # the hop answered, but broken (chaos drop, a
                        # compute crash): steer fresh picks away for a
                        # beat. 503 is EXEMPT — a shed/draining replica
                        # told us when to come back, it isn't sick.
                        self._note_peer_failure(node_id)
                    return web.Response(status=status, body=raw)
                except (OSError, asyncio.TimeoutError, aiohttp.ClientError) as e:
                    last_err = e
                    self._note_peer_failure(node_id)
                    exclude.add(node_id)
                    if session_id is not None:
                        # the replica (and this session's KV on it) is gone
                        self._session_next.pop((session_id, stage), None)
                    self.metrics.inc("hop.dead")
                    self.journal.emit(
                        "peer.dead", trace=tin, peer=node_id, stage=stage,
                        error=f"{type(e).__name__}: {e}"[:120],
                    )
                    log.warning("next hop %s for stage %d unreachable: %s", node_id, stage, e)
            return self._error_response(502, f"next hop unreachable: {last_err}")
        finally:
            if relay_ctx is not None:
                self.tracer.record_span(
                    "relay", phase, t_wall, tracelib.now(), parent=tin,
                    ctx=relay_ctx,
                    attrs={"stage": stage, **(span_attrs or {})},
                )

    async def _post_forward_raw(
        self, value: Dict[str, Any], body: bytes, timeout_s: float
    ) -> Tuple[int, bytes]:
        """One /forward POST to a gossip record -> (status, raw reply)."""
        assert self._http is not None
        host, port = node_addr(value)
        async with self._http.post(
            f"http://{host}:{port}{FORWARD_PATH}", data=body,
            timeout=aiohttp.ClientTimeout(total=timeout_s),
        ) as r:
            return r.status, await r.read()

    def _hedge_delay_s(self, timeout_s: float) -> float:
        """How long to wait on the primary before firing the hedge:
        hedge_delay_ms when pinned (tests/ops), else the trailing-window
        hop p95 ("The Tail at Scale": hedge only the slowest ~5%), with a
        250 ms fallback while the window is empty. Never more than half
        the hop timeout — a hedge that can't finish is pure waste."""
        if self.hedge_delay_ms > 0:
            d = self.hedge_delay_ms / 1e3
        else:
            q = self.tsdb.trailing_quantiles(
                "hop.relay_ms", self.window_s, qs=(0.95,)
            )
            d = q["p95_ms"] / 1e3 if q else 0.25
        return max(0.001, min(d, timeout_s * 0.5))

    def _hedge_target(
        self, session_id: Optional[str], stage: int, exclude: set
    ):
        """(node_id, value) to hedge at, or None. "advertised" (default):
        only a replica whose gossip record advertises this session's KV —
        it can serve the decode step without a session restart, so the
        hedge is genuinely idempotent. "any": the best-ranked OTHER
        replica (stateless backends, where any replica can serve)."""
        if self.hedge_mode == "any":
            ranked = self.path_finder.find_ranked(stage, exclude=exclude)
            return ranked[0] if ranked else None
        if session_id is None:
            return None
        nid = self._gossip_session_holder(session_id, stage, exclude=exclude)
        if nid is None:
            return None
        value = self.dht.get_stage(stage).get(nid)
        return None if value is None else (nid, value)

    async def _relay_exchange(
        self, body: bytes, stage: int, node_id: str, value: Dict[str, Any],
        timeout_s: float, session_id: Optional[str], exclude: set,
        allow_hedge: bool, tin: Optional[tracelib.SpanContext],
    ) -> Tuple[int, bytes]:
        """One hop exchange, optionally hedged: POST the primary; if it
        hasn't answered within the hedge delay and a target + budget
        exist, POST the identical bytes at the second replica and take
        the FIRST 200, cancelling the loser (hedge.fired/won/cancelled
        counters + journal).

        Resolution rules: ANY primary response — 200 or not — concludes
        the exchange immediately (the pre-hedge contract: a deterministic
        409/500 from the picked replica must reach the caller's
        retry/re-pick logic at once, not after the hedge resolves); a
        hedge response concludes it only on 200 (a fast 409 from a
        KV-less hedge target must not mask the primary's real answer).
        When the primary DIES at transport level the hedge gets its
        chance (it fired because the primary already stalled, so its
        answer is normally already in hand); if neither succeeds the
        primary's outcome is raised, keeping the caller's dead-hop
        bookkeeping about the replica it actually picked."""
        primary = asyncio.ensure_future(
            self._post_forward_raw(value, body, timeout_s)
        )
        hedge_to = None
        if allow_hedge:
            done, _ = await asyncio.wait(
                {primary}, timeout=self._hedge_delay_s(timeout_s)
            )
            if primary in done:
                return primary.result()  # may raise: caller handles
            hedge_to = self._hedge_target(
                session_id, stage, exclude={node_id, *exclude}
            )
            if hedge_to is not None and not self.hedge_budget.try_acquire():
                hedge_to = None  # over the <=5% extra-load budget
        if hedge_to is None:
            return await primary
        hid, hvalue = hedge_to
        self.metrics.inc("hedge.fired")
        self.journal.emit(
            "hedge.fired", trace=tin, stage=stage, primary=node_id,
            hedge=hid, session=session_id,
        )
        hedge = asyncio.ensure_future(
            self._post_forward_raw(hvalue, body, timeout_s)
        )
        outcomes: Dict[Any, Any] = {}
        pending = {primary, hedge}
        try:
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for t in done:
                    try:
                        status, raw = t.result()
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:
                        outcomes[t] = e
                        continue
                    if t is primary:
                        # the picked replica ANSWERED: that is the
                        # exchange's result, 200 or not — the hedge
                        # only ever covers a primary that stays silent
                        self.metrics.inc("hedge.cancelled")
                        return status, raw
                    if status == 200:
                        self.metrics.inc("hedge.won")
                        self.journal.emit(
                            "hedge.won", trace=tin, stage=stage,
                            hedge=hid, session=session_id,
                        )
                        if session_id is not None:
                            # the winner proved it holds/serves this
                            # session: repoint affinity so the next
                            # step goes straight there
                            key = (session_id, stage)
                            self._session_next[key] = (
                                hid, time.monotonic()
                            )
                            self._session_next.move_to_end(key)
                        return status, raw
                    outcomes[t] = (status, raw)
        finally:
            # whatever got us out (a winner, both losing, cancellation):
            # no in-flight copy survives this exchange
            for t in (primary, hedge):
                if not t.done():
                    t.cancel()
            await asyncio.gather(primary, hedge, return_exceptions=True)
        # reaching here means BOTH tasks resolved without a definitive
        # answer; a primary RESPONSE always returned in-loop, so the
        # primary's outcome is necessarily its exception — raise it (the
        # caller's dead-hop bookkeeping is about the replica it picked)
        pr = outcomes.get(primary)
        assert isinstance(pr, Exception), pr
        raise pr

    async def handle_import_session(self, request: web.Request) -> web.Response:
        """Adopt a migrating replica's session KV (live-migration handoff —
        see change_stage). POST {"session_id", "stage", "k", "v", "length"}
        -> {"ok": bool}. Only accepted for this node's current stage."""
        try:
            env = wire.unpack(await request.read())
            session_id = env["session_id"]
            stage = int(env["stage"])
        except Exception as e:
            return self._error_response(400, f"bad import_session: {e}")
        if stage != self.info.stage:
            return self._error_response(
                409, f"wrong stage: this node serves {self.info.stage}, not {stage}",
                code="wrong_stage",
            )
        imp = getattr(self.executor, "import_session", None)
        ok = False
        # handoff-phase span, parented to the exporter's span riding the
        # envelope: the adoption cost shows up in the same trace as the
        # export that shipped it
        parent = tracelib.SpanContext.from_wire(env.get(tracelib.WIRE_KEY))
        t_wall = tracelib.now()
        if imp is not None:
            try:
                ok = bool(await self.scheduler.run(imp, session_id, env))
            except Exception:
                log.exception("import_session failed")
        self.tracer.record_span(
            "import_session", "handoff", t_wall, tracelib.now(), parent=parent,
            attrs={"stage": stage, "ok": ok},
        )
        if ok:
            self.metrics.inc("sessions.imported")
            # advertise the adopted session NOW: the failed-over client's
            # next chunk routes here via the gossip session location, and
            # waiting for the next request-driven announce would race it
            self.announce()
        return web.Response(body=wire.pack({"ok": ok}))

    async def handle_export_session(self, request: web.Request) -> web.Response:
        """Deliberate single-session handoff — the DISAGGREGATED
        prefill->decode primitive: POST {"session_id", "target_host",
        "target_port"} exports that session's KV, ships it to the target
        replica's /import_session, and (on success) drops it here; the
        caller continues decoding against the target TOKEN-EXACT with zero
        restarts. A prefill-heavy request can land on any replica, prefill
        there, and decode somewhere cheaper — the reference pins a
        session's KV to one server forever
        (/root/reference/models/qwen3/server/qwen3_server_module.py:220).
        Replies {"ok": true, "bytes": N, "ms": T}; /stats carries the
        cumulative handoff.bytes counter and handoff.ms histogram."""
        try:
            env = wire.unpack(await request.read())
            session_id = env["session_id"]
            host = str(env["target_host"])
            port = int(env["target_port"])
        except Exception as e:
            return self._error_response(400, f"bad export_session: {e}")
        export = getattr(self.executor, "export_sessions", None)
        if export is None:
            return self._error_response(
                501, "this executor cannot export sessions", code="no_export"
            )
        t0 = time.perf_counter()
        try:
            exported = await self.scheduler.run(
                lambda: export(only=session_id)
            )
        except Exception as e:
            return self._error_response(500, f"export failed: {e}")
        if not exported:
            return self._error_response(
                404, f"no session {session_id} here", code="unknown_session"
            )
        sid, payload = exported[0]
        # handoff-phase span: its id rides the import envelope so the
        # importer's adoption span nests under this export in the merged
        # timeline (the disaggregated prefill->decode hop, attributable)
        h_parent = tracelib.SpanContext.from_wire(env.get(tracelib.WIRE_KEY))
        hctx: Optional[tracelib.SpanContext] = None
        t_wall = tracelib.now()
        if tracelib.enabled():
            hctx = tracelib.SpanContext(
                h_parent.trace_id if h_parent is not None else tracelib.new_id(),
                tracelib.new_id(),
            )
        body = wire.pack({
            "session_id": sid, "stage": self.info.stage, **payload,
            **({tracelib.WIRE_KEY: hctx.to_wire()} if hctx is not None else {}),
        })
        assert self._http is not None
        try:
            async with self._http.post(
                f"http://{host}:{port}{IMPORT_SESSION_PATH}", data=body
            ) as r:
                raw = await r.read()
                try:
                    resp = wire.unpack(raw) if r.status == 200 else None
                except Exception:
                    resp = None  # garbage 200 body == declined, not a 500
        except (OSError, asyncio.TimeoutError, aiohttp.ClientError) as e:
            return self._error_response(502, f"target unreachable: {e}")
        if not (isinstance(resp, dict) and resp.get("ok")):
            return self._error_response(
                502, f"target declined the session: {resp}", code="import_failed"
            )
        # the target owns the session now: drop the local copy so the
        # lane/slot frees (the caller's next step goes to the target)
        end = getattr(self.executor, "end_session", None)
        if end is not None:
            try:
                await self.scheduler.run(end, session_id)
            except Exception:
                log.exception("local end_session after handoff failed")
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.inc("handoff.bytes", len(body))
        self.metrics.observe("handoff.ms", ms)
        self.metrics.inc("sessions.handed_off")
        if hctx is not None:
            self.tracer.record_span(
                "export_session", "handoff", t_wall, tracelib.now(),
                parent=h_parent, ctx=hctx,
                attrs={"stage": self.info.stage, "bytes": len(body)},
            )
        self.announce()  # stop advertising the departed session promptly
        return web.Response(body=wire.pack({
            "ok": True, "bytes": len(body), "ms": round(ms, 3),
        }))

    async def handle_drain(self, request: web.Request) -> web.Response:
        """POST /drain — graceful drain: stop admitting NEW sessions
        (typed 503 code "draining" with a Retry-After hint), gossip a
        `draining` flag both routers treat as an exclusion, then finish
        or hand off resident sessions: after a bounded settle (optional
        body key "wait_s", default 5 s — lets in-flight steps reach a
        chunk boundary) every resident session's KV ships to a surviving
        same-stage replica (/import_session) and the adopted copies drop
        here, so failed-over clients continue token-exact via the gossip
        session-location rescue instead of restarting. Residents no
        replica adopts keep being served HERE until they finish or TTL
        out (drain never kills live work). Idempotent; replies
        {"ok", "draining", "resident", "handed_off"}."""
        env: Dict[str, Any] = {}
        try:
            raw = await request.read()
            if raw:
                parsed = wire.unpack(raw)
                if isinstance(parsed, dict):
                    env = parsed
        except Exception:
            pass  # an empty/garbage body still means "drain"
        try:
            wait_s = float(env.get("wait_s", 5.0))
        except (TypeError, ValueError):
            wait_s = 5.0
        if not self._draining:
            self._draining = True
            self.metrics.inc("drain.requests")
            self.journal.emit("node.draining", stage=self.info.stage)
            self._health_cache = (0.0, None)  # verdict predates the flag
            # urgent: routers must exclude this replica within one gossip
            # beat, not one cache lifetime
            self.announce()
        deadline = time.monotonic() + max(0.0, wait_s)
        while time.monotonic() < deadline and self.scheduler.inflight > 0:
            await asyncio.sleep(0.05)
        store = getattr(self.executor, "sessions", None)
        try:
            resident = len(store) if store is not None else 0
        except TypeError:
            resident = 0
        handed = await self._drain_handoff()
        self.journal.emit(
            "node.drained", stage=self.info.stage, resident=resident,
            handed_off=handed,
        )
        return web.Response(body=wire.pack({
            "ok": True, "draining": True, "resident": resident,
            "handed_off": handed,
        }))

    async def _drain_handoff(self) -> int:
        """Ship every resident session's KV to surviving same-stage
        replicas and drop the local copy of each ADOPTED one (unlike the
        stop()-path handoff, this node keeps serving — un-adopted
        sessions must stay resident). Returns how many handed off."""
        export = getattr(self.executor, "export_sessions", None)
        if export is None or self._http is None:
            return 0
        try:
            loop = asyncio.get_running_loop()
            exported = await loop.run_in_executor(None, export)
        except Exception:
            log.exception("drain export failed (residents stay local)")
            return 0
        if not exported:
            return 0
        exported_len = {
            sid: int(payload.get("length", -1)) for sid, payload in exported
        }
        adopted = await self._handoff_sessions(exported, self.info.stage)
        dropped = 0
        for sid in adopted:
            # mid-session chunks are deliberately never shed, so a decode
            # step may have ADVANCED this session while its snapshot was
            # in flight — dropping the newer local copy would strand the
            # client on the adopter's stale KV (409 -> full restart).
            # Re-export just this session and compare frontiers: advanced
            # means it keeps being served HERE (drain finishes residents
            # it can't hand off cleanly; the adopter's stale copy TTLs
            # out). A step landing between this check and end_session
            # still degrades to the client's restart path — containment
            # narrows the race, correctness never depended on it.
            try:
                again = export(only=sid)
            except Exception:
                continue  # can't verify: keep the local copy
            cur_len = (
                int(again[0][1].get("length", -2)) if again else -2
            )
            if cur_len != exported_len.get(sid, -1):
                continue
            try:
                self.executor.end_session(sid)
                dropped += 1
            except Exception:
                log.exception("drain: local end_session failed")
        if dropped:
            self.metrics.inc("drain.handed_off", dropped)
            self.announce()  # stop advertising the departed sessions NOW
        return dropped

    async def _handoff_sessions(self, exported, old_stage: int):
        """Ship a migrating executor's session KV to the live replicas of
        the stage being vacated, so in-flight generations continue without
        a client-side session restart (the reference's migration loses all
        sessions; SURVEY §7 'their KV lives on the old node'). Best effort:
        a failed import just means that session's next chunk 409s and the
        client restarts — exactly the pre-handoff behavior. Returns the
        session ids a replica actually adopted (the drain path drops its
        local copies of exactly those)."""
        assert self._http is not None
        replicas = {
            nid: val
            for nid, val in self.dht.get_stage(old_stage).items()
            if nid != self.info.node_id
        }
        if not replicas:
            return []

        async def ship(sid, payload):
            # per-session handoff span; its id rides the import envelope so
            # the adopter's span joins the same trace
            hctx: Optional[tracelib.SpanContext] = None
            if tracelib.enabled():
                hctx = tracelib.SpanContext(tracelib.new_id(), tracelib.new_id())
            t_wall = tracelib.now()
            adopted = False
            # pack INSIDE the per-session scope: one unserializable session
            # must not abort every other session's handoff
            body = wire.pack({
                "session_id": sid, "stage": old_stage, **payload,
                **({tracelib.WIRE_KEY: hctx.to_wire()} if hctx is not None else {}),
            })
            # a tenant session's payload only goes to adapter-CAPABLE
            # peers (gossiped `ada` key, present even when empty): an
            # old-release or registry-less replica would silently adopt
            # it onto the base weights — its handoff codec ignores the
            # unknown `adapter` key instead of declining
            targets = replicas if payload.get("adapter") is None else {
                nid: val for nid, val in replicas.items() if "ada" in val
            }
            try:
                for nid, val in targets.items():
                    host, port = node_addr(val)
                    try:
                        async with self._http.post(
                            f"http://{host}:{port}{IMPORT_SESSION_PATH}", data=body
                        ) as r:
                            raw = await r.read()
                            resp = wire.unpack(raw) if r.status == 200 else None
                        if isinstance(resp, dict) and resp.get("ok"):
                            self.metrics.inc("sessions.exported")
                            adopted = True
                            return sid  # one adopting replica is enough
                    except Exception:
                        # anything wrong with THIS replica (dead, garbage body,
                        # version mismatch) must not abort the other replicas or
                        # the other sessions' handoffs
                        continue
            finally:
                if hctx is not None:
                    self.tracer.record_span(
                        "handoff", "handoff", t_wall, tracelib.now(), ctx=hctx,
                        attrs={"stage": old_stage, "ok": adopted},
                    )

        # ship sessions concurrently: a dead replica costs ~one hop timeout
        # total, not S * timeout serially (reassign awaits this handoff);
        # return_exceptions so one bad session can't abort its siblings
        results = await asyncio.gather(
            *(ship(s, p) for s, p in exported), return_exceptions=True
        )
        adopted_sids = []
        for r in results:
            if isinstance(r, BaseException):
                log.warning("session handoff failed for one session: %s", r)
            elif r:
                adopted_sids.append(r)
        return adopted_sids

    async def handle_reassign(self, request: web.Request) -> web.Response:
        """Admin-forced migration: POST {"stage": int} (reference
        node.py:82-91, functioning)."""
        try:
            env = wire.unpack(await request.read())
            target = int(env["stage"])
        except Exception as e:
            return self._error_response(400, f"bad reassign request: {e}")
        if not 0 <= target < self.info.num_stages:
            return self._error_response(400, f"stage {target} out of range")
        try:
            await self.change_stage(target)
        except Exception as e:
            log.exception("reassign failed")
            return self._error_response(500, f"reassign failed: {e}")
        return web.Response(body=wire.pack({"ok": True, "stage": target}))

    async def handle_fork_session(self, request: web.Request) -> web.Response:
        """Seed a new session's KV from an existing session's prefix, here
        and on downstream stages (distributed prefix caching — see
        executor.fork_session). POST {"session_id", "parent_session_id",
        "prefix_len", "stage", "relay"}. Responds {"ok": bool, "stage": N};
        ok is True only if EVERY stage from here on forked. A False is a
        clean miss (parent evicted/unknown here — all serving executors
        implement fork_session; getattr guards custom ones that don't) —
        the client falls back to a full prefill."""
        try:
            env = wire.unpack(await request.read())
            new_sid = env["session_id"]
            parent_sid = env["parent_session_id"]
            prefix_len = int(env["prefix_len"])
        except Exception as e:
            return self._error_response(400, f"bad fork_session: {e}")
        stage = int(env.get("stage", self.info.stage))
        relay = env.get("relay", True)

        if stage != self.info.stage:
            if not relay:
                return self._error_response(
                    409,
                    f"wrong stage: this node serves {self.info.stage}, not {stage}",
                    code="wrong_stage",
                )
            try:
                return await self._relay_fork(env, stage)
            except NoNodeForStage as e:
                return self._error_response(503, str(e))

        fork = getattr(self.executor, "fork_session", None)
        ok = False
        if fork is not None:
            try:
                ok = bool(
                    await self.scheduler.run(fork, new_sid, parent_sid, prefix_len)
                )
            except Exception:
                log.exception("fork_session failed")
                ok = False
        self.metrics.inc("fork.ok" if ok else "fork.miss")
        if not ok:
            return web.Response(body=wire.pack({"ok": False, "stage": stage}))
        if not relay or stage + 1 >= self.info.num_stages:
            return web.Response(body=wire.pack({"ok": True, "stage": stage}))
        # downstream stages must fork the same parent; a partially-forked
        # chain reports ok=False and the client's end_session cleans it up
        next_env = dict(env, stage=stage + 1)
        try:
            return await self._relay_fork(next_env, stage + 1)
        except NoNodeForStage as e:
            return self._error_response(503, f"no next node for fork: {e}")

    async def _relay_fork(self, env: Dict[str, Any], stage: int) -> web.Response:
        """Relay a fork along the PARENT session's affinity route (the
        replicas actually holding the parent's KV), pinning the new
        session's affinity to the same replicas as it goes.

        ONE attempt, no re-pick: only the parent's replica can hold its KV —
        a different replica would answer a misleading clean ok=False miss
        (which makes the client permanently unpin a prefix that survived a
        network blip). A transport failure surfaces as a 502 instead, which
        the client treats as transient (pin kept, full prefill this once)."""
        assert self._http is not None
        parent_sid = env.get("parent_session_id")
        new_sid = env.get("session_id")
        body = wire.pack(env)
        node_id, value = await self._pick_next(parent_sid, stage)
        host, port = node_addr(value)
        url = f"http://{host}:{port}{FORK_SESSION_PATH}"
        try:
            async with self._http.post(url, data=body) as r:
                raw = await r.read()
                if r.status == 200 and new_sid is not None:
                    key = (new_sid, stage)
                    self._session_next[key] = (node_id, time.monotonic())
                    self._session_next.move_to_end(key)
                return web.Response(status=r.status, body=raw)
        except (OSError, asyncio.TimeoutError, aiohttp.ClientError) as e:
            self.metrics.inc("hop.dead")
            self.journal.emit(
                "peer.dead", peer=node_id, stage=stage,
                error=f"{type(e).__name__}: {e}"[:120],
            )
            return self._error_response(502, f"fork hop unreachable: {e}")

    def _build_spec_engine(self, sampling):
        """Self-drafting speculative engine over the executor's full-model
        params: the target's first `spec_draft_layers` layers propose,
        the full stack verifies — token-exact for greedy requests and
        DISTRIBUTION-exact (standard rejection scheme) for sampled ones
        (core.speculative). Only possible when this node hosts the whole
        model with addressable params (stage or batched executor; the mesh
        executor's params are sharded). `sampling` is baked into the
        engine's jits; the caller caches one engine per config."""
        if (
            self.spec_draft_layers <= 0
            or self.info.num_stages != 1
            or self.spec_draft_layers >= self.cfg.num_layers
            or self.mesh_plan is not None  # mesh params are pp/tp-sharded
            # batched executors speculate on their own lanes
            # (core.spec_batch) — a second solo engine would double the
            # cache memory to serve one request at a time
            or getattr(self.executor, "spec_enabled", lambda: False)()
        ):
            return False
        params = getattr(self.executor, "params", None)
        if params is None:
            eng = getattr(self.executor, "engine", None)
            params = getattr(eng, "params", None)
        if not isinstance(params, dict) or "embed" not in params:
            return False
        from inferd_tpu.core.speculative import SpeculativeEngine, self_draft

        dcfg, draft_params = self_draft(self.cfg, params, self.spec_draft_layers)
        return SpeculativeEngine(
            self.cfg, params, dcfg, draft_params, k=self.spec_k,
            max_len=self.max_len,
            sampling_cfg=sampling,
            top_n=self._spec_top_n,
        )

    async def handle_generate(self, request: web.Request) -> web.Response:
        """Traced entry for /generate: the X-Inferd-Trace header (the
        trace surface of this endpoint — there is no per-hop envelope on
        the outer request) parents a `server`-phase umbrella span, and the
        contextvar makes every span of the node's self-driven token loop
        (its swarm client's steps, the /forward hops they trigger) nest
        under it. NOT phase "sample": the merge CLI counts sample-phase
        spans as emitted tokens, and an umbrella would inflate every
        server-driven generation by one. With tracing disabled this is a
        passthrough."""
        # user-SLI accounting for this request: wall/ttft/token stamps
        # collected by the inner paths, folded into the generate.* series
        # on the way out — UNLESS the X-Inferd-Canary header marks it
        # synthetic (obs.canary): probe traffic must never flatter or
        # poison the numbers users are judged by. Canary requests tag
        # their server span instead, so traces stay attributable.
        is_canary = request.headers.get(canarylib.CANARY_HEADER) is not None
        sli: Dict[str, Any] = {
            "t0": time.perf_counter(), "ttft_ms": None, "tokens": 0,
            "canary": is_canary,
        }
        status = 500  # an exception escaping the handler IS a server error
        try:
            if not tracelib.enabled():
                resp = await self._handle_generate_inner(request, sli)
            else:
                parent = tracelib.SpanContext.from_header(
                    request.headers.get(tracelib.TRACE_HEADER)
                )
                with self.tracer.span(
                    "generate", "server", parent=parent,
                    attrs={"canary": 1} if is_canary else None,
                ):
                    resp = await self._handle_generate_inner(request, sli)
            status = resp.status
            return resp
        finally:
            self._record_generate_sli(sli, status)

    def _record_generate_sli(self, sli: Dict[str, Any], status: int) -> None:
        """Fold one finished /generate into the user-SLI series —
        generate.requests/errors counters plus the wall_ms/ttft_ms/
        tpot_ms/tokens series the windowed tsdb turns into fleet
        TTFT/TPOT percentiles and the availability burn-rate SLI
        (obs.fleet, obs.health BURN_SLIS). Canary-tagged requests are
        excluded by construction. Only SUCCESSFUL responses record
        latency: a fast 503 shed or 400 reject folded into wall_ms
        would DROP the fleet percentiles during the exact incident
        they exist to expose (errors burn the error budget instead).
        The whole family rides the INFERD_EVENTS kill switch so a
        disabled node's /metrics stays byte-identical."""
        if sli["canary"] or not eventslib.enabled():
            return
        m = self.metrics
        m.inc("generate.requests")
        if sli.get("error"):
            # a STREAMED failure rides an already-sent 200: the handler
            # wrote an {"error": ...} line instead of a status code, so
            # the in-band marker — not resp.status — is the truth here
            status = 500
        if status >= 400:
            if status >= 500:
                m.inc("generate.errors")  # 4xx = caller bug, not burn
            return
        wall_ms = (time.perf_counter() - sli["t0"]) * 1e3
        m.observe("generate.wall_ms", wall_ms, bounds_ms=_GENERATE_BOUNDS_MS)
        n = int(sli.get("tokens") or 0)
        if n > 0:
            m.inc("generate.tokens", n)
            m.observe("generate.tpot_ms", wall_ms / n)
        if sli.get("ttft_ms") is not None:
            m.observe(
                "generate.ttft_ms", sli["ttft_ms"],
                bounds_ms=_GENERATE_BOUNDS_MS,
            )

    async def _handle_generate_inner(
        self, request: web.Request, sli: Optional[Dict[str, Any]] = None,
    ) -> web.Response:
        """Server-driven generation: ONE request returns a whole generation.

        The client-side token loop (client.base) costs a network round trip
        per token — fine on a LAN, ruinous for a high-latency client. Here
        the NODE runs that same loop against itself (the swarm client
        pointed at this node's own /forward; wrong-stage entry relays to
        stage 0 as usual), so the caller pays one round trip total. POST
        {"prompt_ids": [...], "max_new_tokens", "sampling": {temperature,
        top_k, top_p, min_p}, "seed", "eos_token_id", "pin_prefix_len",
        "stream"} -> {"ids": [...]}.  pin_prefix_len > 0 marks the first N
        prompt ids as a shared prefix: the node pins them once (a node-held
        pinned session) and forks it for this and later generations.

        stream=true switches to a chunked newline-delimited-JSON response:
        one {"t": id} line per sampled token as it is produced, a
        {"restart": true} line if a mid-generation failure forces a
        deterministic re-run (previously streamed tokens are void), and a
        final {"done": true, "ids": [...]} (or {"error": ...}) line.

        Seed contract for SAMPLED (temperature > 0) requests: on batched
        and mesh nodes the speculative lane path is chosen structurally
        (per request shape, never per load), so a repeated (prompt, seed,
        sampling) request replays the same stream. On single-stage SOLO
        nodes with --spec-draft-layers the fast path is opportunistic —
        a request arriving while the solo spec engine is busy takes the
        regular loop, whose key schedule differs from the rejection-
        sampled engine's — so identical sampled requests under CONCURRENT
        load may return different (identically distributed) streams.
        Clients needing exact sampled replay should use greedy, logprobs
        (which pins the regular loop), or a batched/mesh node."""
        from inferd_tpu.config import SamplingConfig

        if self._draining:
            # a /generate is a NEW server-driven session by definition:
            # drain sheds it before any parsing or pinning happens
            return self._error_response(
                503, "node is draining: not accepting new generations",
                code="draining", retry_after=self._retry_after_s(),
            )
        try:
            env = wire.unpack(await request.read())
            ids = [int(t) for t in env["prompt_ids"]]
            if not ids:
                raise ValueError("prompt_ids must be non-empty")
            max_new = int(env.get("max_new_tokens", 50))
            seed = int(env.get("seed", 0))
            eos = env.get("eos_token_id")
            eos = None if eos is None else int(eos)
            pin_len = int(env.get("pin_prefix_len", 0))
            stream = bool(env.get("stream", False))
            want_lp = bool(env.get("logprobs", False))
            top_n = int(env.get("top_logprobs", 0))
            if top_n < 0 or top_n > 64:
                raise ValueError(f"top_logprobs {top_n} out of range [0, 64]")
            # tolerate unknown sampling keys: a NEWER client talking to
            # this node mid-rolling-upgrade must not 400 on a knob this
            # version doesn't know (the mirror of the client omitting
            # default-valued new keys)
            known = {f.name for f in dataclasses.fields(SamplingConfig)}
            raw_sampling = dict(env.get("sampling") or {})
            ignored_keys = sorted(set(raw_sampling) - known)
            if ignored_keys:
                # observable, not fatal: a typo'd knob or a newer client's
                # feature silently changing sampling semantics is worse
                # than a log line + an echo in the payload
                log.warning(
                    "ignoring unknown sampling keys %s", ignored_keys
                )
            sampling = SamplingConfig(
                **{k: v for k, v in raw_sampling.items() if k in known}
            )
        except Exception as e:
            return self._error_response(400, f"bad generate request: {e}")
        if pin_len < 0 or pin_len > len(ids):
            return self._error_response(400, f"pin_prefix_len {pin_len} out of range")
        # optional end-to-end deadline on the WHOLE server-driven
        # generation (epoch ms, same key as the /forward envelopes): an
        # already-expired budget sheds here, and the regular token loop
        # carries the remainder so every inner hop fast-fails on time
        gen_rem = retrylib.remaining_s(env.get(retrylib.DEADLINE_KEY))
        if gen_rem is not None and gen_rem <= 0:
            self.metrics.inc("deadline.expired")
            self.journal.emit(
                "deadline.exceeded", stage=self.info.stage, where="generate"
            )
            return self._error_response(
                408, "deadline exceeded (generate admission)", code="deadline"
            )

        # batched/mesh nodes speculate on their ENGINE LANES/SLOTS
        # (core.spec_batch / parallel.infer): concurrent requests' rounds
        # coalesce instead of shedding to the regular loop, streamed
        # requests emit each accepted run as it lands, and PINNED-PREFIX
        # requests fork the shared pin instead of re-prefilling. Greedy is
        # token-exact with the regular loop; sampled is distribution-exact
        # (no per-token logprob trail — logprob requests take the regular
        # loop).
        if (
            self.spec_draft_layers > 0
            and getattr(self.executor, "spec_enabled", lambda: False)()
            and (
                (
                    # greedy: logprobs/top-N ride the verify chunk's TARGET
                    # logits (the runners' static SPEC_TOP_N width);
                    # streamed lp keeps the regular loop (per-token lp
                    # lines)
                    sampling.temperature == 0.0
                    and not (stream and (want_lp or top_n))
                    and top_n <= self._spec_top_n
                )
                or (sampling.temperature > 0.0 and not want_lp and top_n == 0)
            )
        ):
            if stream:
                return await self._generate_streaming_lanes(
                    request, ids, max_new, eos, seed, sampling, ignored_keys,
                    pin_len=pin_len, sli=sli,
                )
            resp = await self._generate_speculative_lanes(
                ids, max_new, eos, seed, sampling, ignored_keys,
                pin_len=pin_len, want_lp=want_lp, top_n=top_n, sli=sli,
            )
            if resp is not None:
                return resp

        # unpinned requests take the speculative fast path when the node
        # was started with --spec-draft-layers. Greedy requests get the
        # token-exact draft-propose/verify loop (the caller cannot tell
        # except by latency; logprobs ride along from the verify chunk's
        # TARGET logits up to the engine's static top-N width). Sampled
        # (temperature > 0) requests get the rejection-sampled engine —
        # the emitted stream is DISTRIBUTED exactly as target-only
        # sampling (not token-identical to the regular loop's key
        # schedule; a given (engine, seed) is still deterministic) — but
        # have no per-token logprob trail, so logprob requests take the
        # regular loop. Streamed requests emit each accepted run as it
        # lands (logprob streams keep the regular loop: its per-token
        # lines carry lp fields the run-level hook doesn't).
        if (
            pin_len == 0
            and self.spec_draft_layers > 0
            and (
                (
                    sampling.temperature == 0.0
                    # streamed requests skip the fast path only when they
                    # also want logprobs/top-N (the run-level stream hook
                    # carries no per-token lp fields)
                    and not (stream and (want_lp or top_n))
                    and top_n <= self._spec_top_n
                )
                or (sampling.temperature > 0.0 and not want_lp and top_n == 0)
            )
            and not self._spec_lock.locked()  # opportunistic: a busy spec
            # engine must not serialize concurrent requests behind it —
            # waiters take the regular (batchable) loop instead
        ):
            if stream:
                return await self._generate_streaming_solo_spec(
                    request, ids, max_new, eos, seed, sampling, ignored_keys,
                    sli=sli,
                )
            resp = await self._generate_speculative(
                ids, max_new, eos, seed, sampling, ignored_keys,
                want_lp=want_lp, top_n=top_n, sli=sli,
            )
            if resp is not None:
                return resp

        c = await self._get_generate_client()
        if stream:
            return await self._generate_streaming(
                request, c, ids, max_new, eos, seed, sampling, pin_len,
                want_lp, ignored_keys, top_n, sli=sli,
            )

        from inferd_tpu.client.base import ServerError

        try:
            lps = [] if want_lp else None
            tops = [] if top_n else None
            if pin_len:
                await c.pin_prefix(ids[:pin_len])
            out = await c.generate_ids(
                ids, max_new_tokens=max_new, eos_token_id=eos, seed=seed,
                sampling=sampling, logprob_sink=lps,
                top_n=top_n, top_sink=tops, deadline_s=gen_rem,
            )
        except ServerError as e:
            # pass the inner status + machine-readable code through: a 409
            # overflow must NOT come back as a retryable-looking 500 (the
            # caller's ServerError.retryable contract)
            return self._error_response(e.status, str(e), code=e.code)
        except Exception as e:
            return self._error_response(500, f"generation failed: {e}")
        if sli is not None:
            sli["tokens"] = len(out)
        payload = {"ids": out, "session_tokens": len(out)}
        if want_lp:
            payload["logprobs"] = lps
        if tops is not None:
            payload["top_logprobs"] = [list(t) for t in tops]
        if ignored_keys:
            payload["ignored_sampling_keys"] = ignored_keys
        return web.Response(body=wire.pack(payload))

    async def _get_generate_client(self):
        """Lazy self-pointed swarm client shared by all /generate requests
        (persistent so node-held prefix pins survive across requests)."""
        from inferd_tpu.client.swarm_client import SwarmClient

        async with self._generate_client_lock:
            if self._generate_client is None:
                c = SwarmClient(
                    [(self.info.host, self.info.port)],
                    timeout_s=self.hop_timeout_s,
                )
                # share the NODE's span ring: the self-client's step/sample
                # spans belong in this node's JSONL file, not a parallel
                # "client" buffer nobody exports
                c.tracer = self.tracer
                await c.__aenter__()
                self._generate_client = c
        return self._generate_client

    @staticmethod
    def _spec_key(sampling):
        """(cache key, normalized config) for the per-sampling-config
        speculative engines. Greedy ignores the warp parameters entirely —
        normalize so greedy clients with different top-k/p defaults share
        ONE engine instead of compiling behaviorally identical
        duplicates."""
        if sampling.temperature == 0.0:
            return (0.0, 0, 1.0, 0.0), dataclasses.replace(
                sampling, temperature=0.0, top_k=0, top_p=1.0, min_p=0.0
            )
        return (
            (sampling.temperature, sampling.top_k, sampling.top_p,
             sampling.min_p),
            sampling,
        )

    async def _ensure_spec_engine_locked(self, key, sampling):
        """Build-or-get the speculative engine for `key` (MUST hold
        _spec_lock). None = unsupported/demoted — caller takes the
        regular loop."""
        if self._spec_unsupported:
            return None
        eng = self._spec_engines.get(key)
        if eng is None:
            loop = asyncio.get_running_loop()
            try:
                eng = await loop.run_in_executor(
                    None, self._build_spec_engine, sampling
                )
                if eng is False:
                    # STRUCTURAL: this executor can't self-draft (wrong
                    # topology/params shape) — config-independent, stop
                    # probing until a migration rebuilds the executor
                    self._spec_unsupported = True
                    return None
            except Exception:
                # transient/config-specific build failure: demote THIS
                # config only; other configs may still build fine
                log.exception("speculative engine build failed")
                eng = False
            self._insert_spec_engine_locked(key, eng)
        else:
            self._spec_engines.move_to_end(key)
        return None if eng is False else eng

    def _insert_spec_engine_locked(self, key, eng) -> None:
        """Cache insert + caps (MUST hold _spec_lock). The LRU cap counts
        LIVE engines only: False demotion markers must neither cost a live
        slot (inserting a marker must not evict a compiled engine) nor be
        evicted by live-engine pressure (a demoted config must STAY off —
        re-building it would re-fail and re-log per request)."""
        self._spec_engines[key] = eng
        live = [
            k for k, v in self._spec_engines.items() if v is not False
        ]
        while len(live) > self._spec_engines_max:
            del self._spec_engines[live.pop(0)]  # oldest live
        while len(self._spec_engines) > 64:  # marker flood cap
            self._spec_engines.popitem(last=False)

    async def _prebuild_spec_engine(self) -> None:
        """Background prebuild of the GREEDY speculative engine right
        after start(): the first greedy /generate otherwise pays the whole
        draft+target jit build on its own latency (seconds on CPU, tens of
        seconds for a real model on TPU). Builds OUTSIDE _spec_lock —
        locked() doubles as handle_generate's busy-shed signal, so holding
        it through a multi-second compile would bounce every early greedy
        request to the regular loop (a request racing the prebuild at
        worst duplicates the build; both results are identical and the
        insert is last-writer-wins under the lock)."""
        from inferd_tpu.config import SamplingConfig

        try:
            loop = asyncio.get_running_loop()
            if getattr(self.executor, "spec_enabled", lambda: False)():
                # batched node: warm the GREEDY lane runner's jits with one
                # tiny open/round/close so the first real request doesn't
                # pay the round compile alone
                t0 = time.monotonic()
                await loop.run_in_executor(None, self.executor.spec_warmup)
                self.metrics.observe(
                    "spec.engine_build_ms", (time.monotonic() - t0) * 1e3,
                    bounds_ms=(10, 100, 1000, 10_000, 60_000, 120_000),
                )
                return
            key, sampling = self._spec_key(SamplingConfig(temperature=0.0))
            # capture the executor the build reads: a migrate() swapping
            # the executor mid-build must not leave a stale-params engine
            # in the cache (the insert below is skipped instead)
            built_for = self.executor
            t0 = time.monotonic()
            eng = await loop.run_in_executor(
                None, self._build_spec_engine, sampling
            )
            self.metrics.observe(
                "spec.engine_build_ms", (time.monotonic() - t0) * 1e3,
                bounds_ms=(10, 100, 1000, 10_000, 60_000, 120_000),
            )
            async with self._spec_lock:
                if eng is False:
                    self._spec_unsupported = True
                elif self.executor is not built_for:
                    log.info("executor changed mid-prebuild; dropping engine")
                elif not self._spec_engines.get(key):
                    # insert if absent OR demoted: a racing request's
                    # TRANSIENT build failure may have left a False marker
                    # for this key; the engine in hand is known-good, so
                    # good-engine-wins (the cap logic applies either way)
                    self._insert_spec_engine_locked(key, eng)
        except Exception:
            log.debug("speculative prebuild failed", exc_info=True)

    async def _generate_speculative(
        self, ids, max_new: int, eos, seed: int, sampling, ignored_keys=(),
        want_lp: bool = False, top_n: int = 0,
        sli: Optional[Dict[str, Any]] = None,
    ) -> Optional[web.Response]:
        """Speculative fast path; None = unavailable/failed (caller falls
        back to the regular loop). Logprobs/top-N (greedy only) come from
        the verify chunk's TARGET logits — identical to the regular loop's
        values. One engine per sampling config (LRU-capped): the warp
        parameters are static in the engine's jits."""
        # greedy ignores the warp parameters entirely — normalize the key
        # so greedy clients with different top-k/p defaults share ONE
        # engine instead of compiling behaviorally identical duplicates
        key, sampling = self._spec_key(sampling)
        async with self._spec_lock:
            eng = await self._ensure_spec_engine_locked(key, sampling)
            if eng is None:
                return None
            lps = [] if want_lp else None
            tops = [] if top_n else None
            try:
                out, acceptance, drafted, accepted = await self.scheduler.run(
                    lambda: eng.generate_with_stats(
                        ids, max_new, eos_token_id=eos, seed=seed,
                        logprob_sink=lps, top_sink=tops,
                    )
                )
            except Exception:
                # demote THIS config: a deterministic failure would
                # otherwise re-run (and re-log) on every matching request;
                # its fast path stays off until restart/migration
                log.exception(
                    "speculative generate failed; disabling the fast path "
                    "for this sampling config and falling back to the loop"
                )
                self._spec_engines[key] = False
                self.metrics.inc("generate.speculative_fallback")
                return None
            # production acceptance-rate observability (/stats):
            # spec.proposed/spec.accepted accumulate across requests
            self.metrics.inc("spec.proposed", drafted)
            self.metrics.inc("spec.accepted", accepted)
        self.metrics.inc("generate.speculative")
        if sli is not None:
            sli["tokens"] = len(out)
        payload = {
            "ids": out,
            "session_tokens": len(out),
            "speculative": True,
            "draft_acceptance": acceptance,
            "spec_accept_rate": acceptance,
        }
        if lps is not None:
            payload["logprobs"] = lps
        if tops is not None:
            # the engine reports its static jit width; trim to the request
            payload["top_logprobs"] = [
                [ti[:top_n], tl[:top_n]] for ti, tl in tops
            ]
        if ignored_keys:
            payload["ignored_sampling_keys"] = list(ignored_keys)
        return web.Response(body=wire.pack(payload))

    async def _generate_streaming(
        self, request, c, ids, max_new: int, eos, seed: int, sampling,
        pin_len: int, want_lp: bool = False, ignored_keys=(), top_n: int = 0,
        sli: Optional[Dict[str, Any]] = None,
    ) -> web.StreamResponse:
        """Chunked ndjson streaming flavor of /generate (see handle_generate
        docstring for the line protocol)."""
        import json as jsonlib

        resp = web.StreamResponse(headers={"Content-Type": "application/x-ndjson"})
        resp.enable_chunked_encoding()
        await resp.prepare(request)

        lps = [] if want_lp else None
        tops = [] if top_n else None

        async def on_token(tok):
            if tok is None:
                line = {"restart": True}
                if sli is not None:
                    # restarted: previously streamed tokens are VOID, so
                    # both the count and the first-token stamp reset —
                    # TTFT must mean the first token the user got to keep
                    sli["tokens"] = 0
                    sli["ttft_ms"] = None
            else:
                line = {"t": int(tok)}
                if sli is not None:
                    # user-SLI stamps: TTFT is the FIRST emitted token
                    # (the number a streaming user actually waits on)
                    if sli["ttft_ms"] is None:
                        sli["ttft_ms"] = (
                            time.perf_counter() - sli["t0"]
                        ) * 1e3
                    sli["tokens"] += 1
                if lps is not None:
                    # the loop appends to the sink BEFORE invoking the hook
                    line["lp"] = lps[-1]
                if tops is not None:
                    line["top"] = list(tops[-1])
            await resp.write(jsonlib.dumps(line).encode() + b"\n")

        try:
            if pin_len:
                await c.pin_prefix(ids[:pin_len])
            out = await c.generate_ids(
                ids, max_new_tokens=max_new, eos_token_id=eos, seed=seed,
                sampling=sampling, on_token=on_token, logprob_sink=lps,
                top_n=top_n, top_sink=tops,
            )
            done = {"done": True, "ids": out}
            if lps is not None:
                done["logprobs"] = lps
            if tops is not None:
                done["top_logprobs"] = [list(t) for t in tops]
            if ignored_keys:
                done["ignored_sampling_keys"] = list(ignored_keys)
            await resp.write(jsonlib.dumps(done).encode() + b"\n")
        except Exception as e:
            # the 200 header is already gone — surface the failure as a
            # terminal line instead of a status code, and mark the SLI
            # record so a broken stream burns the error budget instead
            # of polluting the latency percentiles as a "success".
            # Connection-class failures are the CLIENT hanging up, not a
            # server fault — they must not burn availability
            if sli is not None and not isinstance(
                e, (ConnectionResetError, OSError, aiohttp.ClientError)
            ):
                sli["error"] = True
            try:
                await resp.write(
                    jsonlib.dumps({"error": f"{type(e).__name__}: {e}"[:300]}).encode()
                    + b"\n"
                )
            except Exception:
                pass
        try:
            await resp.write_eof()
        except Exception:
            pass  # client disconnected mid-stream: close quietly
        return resp

    async def _run_speculative_lanes(
        self, ids, max_new: int, eos, seed: int, sampling, emit=None,
        pin_len: int = 0, want_lp: bool = False, top_n: int = 0,
        lp_sink=None, top_sink=None,
    ):
        """Drive one /generate request through the batched executor's lane
        speculation (executor.spec_open/spec_step/spec_close). Returns
        (ids, drafted, accepted) or None when the fast path is unavailable
        (no lane, prompt over the spec-capped budget, or a failure) — the
        caller falls back to the regular loop. `emit` (async, called with
        each accepted run as it lands) powers the streaming flavor.
        `pin_len` composes speculation with prefix caching: the node pins
        the prefix once (the regular loop's shared pin) and the spec
        session forks it instead of re-prefilling. `want_lp`/`top_n`
        (greedy only) fill `lp_sink`/`top_sink` with the TARGET model's
        per-token logprob trail from the verify chunks."""
        from inferd_tpu.runtime.batch_executor import CapacityError
        from inferd_tpu.runtime.spec_serving import SpecForkMiss

        ex = self.executor
        if len(ids) + max_new > ex.cap:
            # the regular loop surfaces the overflow with the proper
            # 409/KV-overflow contract; the fast path just declines
            return None
        parent = prefix_logits = None
        if pin_len:
            c = await self._get_generate_client()
            try:
                await c.pin_prefix(ids[:pin_len])
            except Exception:
                log.exception("prefix pin failed; regular loop serves it")
                return None
            ent = c.pinned_parent(ids[:pin_len])
            if ent is None:
                return None
            parent, pin_logits = ent
            if pin_len == len(ids):
                prefix_logits = pin_logits
        want = want_lp or top_n > 0
        sid = "spec-" + uuid.uuid4().hex

        def record(lp, top):
            if lp_sink is not None:
                lp_sink.append(float(lp))
            if top_sink is not None and top is not None:
                ti, tls = top
                top_sink.append((ti[:top_n], tls[:top_n]))

        try:
            first, first_lp = await self.scheduler.run(
                ex.spec_open, sid, ids, sampling, seed, parent, pin_len,
                prefix_logits, want,
            )
        except (CapacityError, BufferError, SpecForkMiss):
            self.metrics.inc("generate.speculative_fallback")
            return None
        except Exception:
            log.exception("lane spec open failed; falling back to the loop")
            self.metrics.inc("generate.speculative_fallback")
            return None
        out = [int(first)]
        if want and first_lp is not None:
            record(first_lp[0], (first_lp[1], first_lp[2]))
        drafted = accepted = 0
        k = ex.spec_k
        try:
            if emit is not None:
                await emit(out[:])
            while len(out) < max_new and (eos is None or out[-1] != eos):
                res = await self.scheduler.run(
                    ex.spec_step, sid, out[-1],
                    out[-2] if len(out) > 1 else 0,
                )
                if res is None:
                    # inside the verify-chunk headroom: finish with plain
                    # batched decode steps (same distribution/greedy stream)
                    tok, tail_lp = await self.scheduler.run(
                        ex.spec_tail_step, sid, out[-1]
                    )
                    out.append(int(tok))
                    if want and tail_lp is not None:
                        record(tail_lp[0], (tail_lp[1], tail_lp[2]))
                    if emit is not None:
                        await emit(out[-1:])
                    continue
                if want:
                    toks, n, lps, tops = res
                else:
                    toks, n = res
                    lps = tops = None
                drafted += k
                accepted += max(0, n - 1)
                run = []
                for j, t in enumerate(toks):
                    out.append(int(t))
                    run.append(int(t))
                    if want:
                        record(lps[j], tops[j])
                    if (eos is not None and t == eos) or len(out) >= max_new:
                        break
                if emit is not None and run:
                    await emit(run)
        finally:
            # OFF the event loop: spec_close takes the executor's step
            # lock, which a concurrent round can hold for a whole device
            # dispatch — blocking here would freeze HTTP + gossip for that
            # long. shield() keeps the close running to completion even if
            # this handler task is being cancelled (client disconnect).
            try:
                await asyncio.shield(
                    asyncio.get_running_loop().run_in_executor(
                        None, ex.spec_close, sid
                    )
                )
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("spec_close failed")
        self.metrics.inc("spec.proposed", drafted)
        self.metrics.inc("spec.accepted", accepted)
        self.metrics.inc("generate.speculative")
        if parent is not None:
            self.metrics.inc("generate.speculative_pinned")
        return out, drafted, accepted

    async def _generate_speculative_lanes(
        self, ids, max_new: int, eos, seed: int, sampling, ignored_keys=(),
        pin_len: int = 0, want_lp: bool = False, top_n: int = 0,
        sli: Optional[Dict[str, Any]] = None,
    ) -> Optional[web.Response]:
        """Non-streamed lane-speculative /generate; None = fall back."""
        lps = [] if want_lp else None
        tops = [] if top_n else None
        try:
            res = await self._run_speculative_lanes(
                ids, max_new, eos, seed, sampling, pin_len=pin_len,
                want_lp=want_lp, top_n=top_n, lp_sink=lps, top_sink=tops,
            )
        except Exception:
            log.exception("lane speculative generate failed; falling back")
            self.metrics.inc("generate.speculative_fallback")
            return None
        if res is None:
            return None
        out, drafted, accepted = res
        if sli is not None:
            sli["tokens"] = len(out)
        rate = accepted / max(drafted, 1)
        payload = {
            "ids": out,
            "session_tokens": len(out),
            "speculative": True,
            "draft_acceptance": rate,
            "spec_accept_rate": rate,
        }
        if lps is not None:
            payload["logprobs"] = lps[: len(out)]
        if tops is not None:
            payload["top_logprobs"] = [list(t) for t in tops[: len(out)]]
        if ignored_keys:
            payload["ignored_sampling_keys"] = ignored_keys
        return web.Response(body=wire.pack(payload))

    async def _stream_spec_common(
        self, request, ids, max_new: int, eos, seed: int, sampling,
        ignored_keys, produce, pin_len: int = 0,
        sli: Optional[Dict[str, Any]] = None,
    ) -> web.StreamResponse:
        """ONE scaffold for both streamed speculative flavors (lane/mesh
        rounds and the solo engine): `produce(emit)` runs the speculative
        generation, calling `await emit(run)` with each accepted run, and
        returns (out, drafted, accepted) — or None for a clean DECLINE
        (nothing emitted), or raises for a mid-flight failure.

        Contract handling lives here exactly once: a decline before any
        byte falls back to the regular streaming loop in-place; a
        mid-flight failure emits {"restart": true} and re-runs on the
        regular loop (streamed tokens are void, per the /generate
        docstring); a CLIENT DISCONNECT mid-stream (emit's write raises)
        aborts quietly — no restart, no wasted re-generation."""
        import json as jsonlib

        resp = web.StreamResponse(
            headers={"Content-Type": "application/x-ndjson"}
        )
        resp.enable_chunked_encoding()
        state = {"prepared": False}

        async def _write(obj) -> None:
            if not state["prepared"]:
                await resp.prepare(request)
                state["prepared"] = True
            await resp.write(jsonlib.dumps(obj).encode() + b"\n")

        async def emit(run):
            try:
                for t in run:
                    await _write({"t": int(t)})
                    if sli is not None:
                        if sli["ttft_ms"] is None:
                            sli["ttft_ms"] = (
                                time.perf_counter() - sli["t0"]
                            ) * 1e3
                        sli["tokens"] += 1
            except (ConnectionResetError, OSError, aiohttp.ClientError) as e:
                raise _ClientGone() from e

        try:
            try:
                res = await produce(emit)
            except _ClientGone:
                return resp  # client hung up: no restart, no re-run
            except Exception:
                log.exception("speculative stream failed")
                self.metrics.inc("generate.speculative_fallback")
                res = None
            if res is None and not state["prepared"]:
                # declined before any byte went out: the regular streaming
                # loop serves the request instead (keeping its prefix pin)
                c = await self._get_generate_client()
                return await self._generate_streaming(
                    request, c, ids, max_new, eos, seed, sampling, pin_len,
                    False, ignored_keys, 0, sli=sli,
                )
            if res is not None:
                out, drafted, accepted = res
                rate = accepted / max(drafted, 1)
                done = {
                    "done": True, "ids": out, "speculative": True,
                    "draft_acceptance": rate, "spec_accept_rate": rate,
                }
            else:
                # mid-flight failure: void the streamed tokens and re-run
                # deterministically on the regular loop (the same contract
                # the non-spec streaming path honors on a node failure)
                await _write({"restart": True})
                if sli is not None:
                    sli["tokens"] = 0
                    sli["ttft_ms"] = None

                async def on_token(tok):
                    if tok is None:
                        if sli is not None:
                            sli["tokens"] = 0
                            sli["ttft_ms"] = None
                        await _write({"restart": True})
                        return
                    await _write({"t": int(tok)})
                    if sli is not None:
                        if sli["ttft_ms"] is None:
                            sli["ttft_ms"] = (
                                time.perf_counter() - sli["t0"]
                            ) * 1e3
                        sli["tokens"] += 1

                c = await self._get_generate_client()
                out = await c.generate_ids(
                    ids, max_new_tokens=max_new, eos_token_id=eos,
                    seed=seed, sampling=sampling, on_token=on_token,
                )
                done = {"done": True, "ids": out}
            if ignored_keys:
                done["ignored_sampling_keys"] = list(ignored_keys)
            await _write(done)
        except Exception as e:
            # broken stream burns, never "succeeds" — unless it's the
            # CLIENT disconnecting (connection-class errors), which is
            # no server fault and must not burn availability
            if sli is not None and not isinstance(
                e, (_ClientGone, ConnectionResetError, OSError,
                    aiohttp.ClientError)
            ):
                sli["error"] = True
            try:
                await _write({"error": f"{type(e).__name__}: {e}"[:300]})
            except Exception:
                pass
        try:
            await resp.write_eof()
        except Exception:
            pass
        return resp

    async def _generate_streaming_solo_spec(
        self, request, ids, max_new: int, eos, seed: int, sampling,
        ignored_keys=(), sli: Optional[Dict[str, Any]] = None,
    ) -> web.StreamResponse:
        """Streamed SOLO-engine speculative /generate (stage-executor
        nodes): the engine's on_tokens hook posts each accepted run from
        the worker thread onto the event loop, which streams it out. The
        decline/restart/disconnect contracts live in _stream_spec_common."""
        key, sampling_n = self._spec_key(sampling)
        loop = asyncio.get_running_loop()

        async def produce(emit):
            async with self._spec_lock:
                eng = await self._ensure_spec_engine_locked(key, sampling_n)
                if eng is None:
                    return None  # decline: regular streaming serves it
                q: asyncio.Queue = asyncio.Queue()

                def on_tokens(run):
                    loop.call_soon_threadsafe(q.put_nowait, list(run))

                gen = asyncio.ensure_future(self.scheduler.run(
                    lambda: eng.generate_with_stats(
                        ids, max_new, eos_token_id=eos, seed=seed,
                        on_tokens=on_tokens,
                    )
                ))
                try:
                    while True:
                        getter = asyncio.ensure_future(q.get())
                        done_set, _ = await asyncio.wait(
                            {getter, gen},
                            return_when=asyncio.FIRST_COMPLETED,
                        )
                        if getter in done_set:
                            run = getter.result()
                        else:
                            getter.cancel()
                            if q.empty():
                                break
                            run = q.get_nowait()
                        await emit(run)
                    out, rate, drafted, accepted = await gen
                except _ClientGone:
                    # the engine thread is uncancellable — let it finish
                    # quietly (per-call caches, no shared state) and keep
                    # its eventual exception from logging as unretrieved
                    gen.add_done_callback(
                        lambda f: f.cancelled() or f.exception()
                    )
                    raise
                except Exception:
                    # deterministic engine failure: demote THIS config like
                    # the non-streamed path (we hold _spec_lock) so every
                    # later matching request doesn't re-fail + re-log
                    self._spec_engines[key] = False
                    raise
                self.metrics.inc("spec.proposed", drafted)
                self.metrics.inc("spec.accepted", accepted)
                self.metrics.inc("generate.speculative")
                return out, drafted, accepted

        return await self._stream_spec_common(
            request, ids, max_new, eos, seed, sampling, ignored_keys, produce,
            sli=sli,
        )

    async def _generate_streaming_lanes(
        self, request, ids, max_new: int, eos, seed: int, sampling,
        ignored_keys=(), pin_len: int = 0,
        sli: Optional[Dict[str, Any]] = None,
    ) -> web.StreamResponse:
        """Streamed lane/slot-speculative /generate (batched and mesh
        executors): each ACCEPTED RUN is emitted the moment its round
        lands. The decline/restart/disconnect contracts live in
        _stream_spec_common."""

        async def produce(emit):
            return await self._run_speculative_lanes(
                ids, max_new, eos, seed, sampling, emit=emit,
                pin_len=pin_len,
            )

        return await self._stream_spec_common(
            request, ids, max_new, eos, seed, sampling, ignored_keys, produce,
            pin_len=pin_len, sli=sli,
        )

    async def handle_end_session(self, request: web.Request) -> web.Response:
        """Drop a session's KV cache here and on downstream stages."""
        try:
            env = wire.unpack(await request.read())
            session_id = env["session_id"]
        except Exception as e:
            return self._error_response(400, f"bad end_session: {e}")
        if (
            env.get("relay", True)
            and not env.get("rescued")
            and not self._holds_session(session_id)
        ):
            # the session's KV for THIS stage lives on another replica (the
            # client ended it via a failed-over entry): forward the end
            # there so the KV is freed now, not at the idle-TTL sweep.
            # One bounce max ("rescued"), best effort.
            holder = self._gossip_session_holder(
                session_id, self.info.stage, exclude={self.info.node_id}
            )
            if holder is not None:
                value = self.dht.get_stage(self.info.stage).get(holder)
                if value is not None:
                    try:
                        assert self._http is not None
                        host, port = node_addr(value)
                        async with self._http.post(
                            f"http://{host}:{port}{END_SESSION_PATH}",
                            data=wire.pack({**env, "rescued": True}),
                        ) as r:
                            body = await r.read()
                        return web.Response(status=r.status, body=body)
                    except Exception:
                        pass  # holder unreachable: TTL sweep collects it
        self.executor.end_session(session_id)
        self.announce(urgent=False)  # stop advertising the session's KV
        if self.replicator is not None:
            # EXPLICIT end: free the session's standby shadow now (fire-
            # and-forget) instead of letting a finished 8k-ctx session's
            # KV copy sit in standby RAM, advertised, for the whole TTL.
            # Only here — mere residency loss (LRU eviction, handoff)
            # must KEEP the shadow, it may be the stream's only copy.
            standby = self.replicator.pop_standby(session_id)
            if standby is not None:
                asyncio.create_task(
                    self._send_standby_drop(session_id, standby)
                )
        stage = int(env.get("stage", self.info.stage))
        if not env.get("relay", True):
            return web.Response(body=wire.pack({"ok": True}))
        if stage + 1 < self.info.num_stages:
            try:
                # follow the session-affinity route so the replica actually
                # holding the KV cache is the one that drops it
                node_id, value = await self._pick_next(session_id, stage + 1)
                host, port = node_addr(value)
                assert self._http is not None
                await self._http.post(
                    f"http://{host}:{port}{END_SESSION_PATH}",
                    data=wire.pack({"session_id": session_id, "stage": stage + 1}),
                )
            except Exception:
                pass  # best effort: the periodic sweep collects orphans
        self._session_next.pop((session_id, stage + 1), None)
        return web.Response(body=wire.pack({"ok": True}))

    async def handle_health(self, request: web.Request) -> web.Response:
        """GET /health — identity plus the SLO verdict: `status` is
        ok|degraded|failing with the firing rules attached, so a load
        balancer (or a human with curl) gets an EVALUATED answer instead
        of four raw numbers to interpret."""
        body = {
            "node": self.info.name,
            "node_id": self.info.node_id,
            "stage": self.info.stage,
            "num_stages": self.info.num_stages,
            "inflight": self.scheduler.inflight,
            "sessions": len(getattr(self.executor, "sessions", [])),
        }
        # the verdict survives INFERD_EVENTS=0: metric-only rules keep
        # evaluating (event rules skip — _health_state passes events=None),
        # so the kill switch sheds journal overhead without blinding the
        # SLO engine; only GOSSIP stays events-gated (announce), keeping
        # the wire byte-identical per the kill-switch contract
        state = self._health_state()
        v = state["verdict"]
        body.update(
            status=v["status"],
            firing=v["firing"],
            rules={"evaluated": v["evaluated"], "skipped": v["skipped"]},
            **{
                k: state["gossip"][k]
                for k in ("hbm", "compiles") if k in state["gossip"]
            },
        )
        wq = self._windowed_gossip()
        if wq:
            # the trailing-window quantiles the verdict was judged on
            # (and the numbers this node gossips) — NOT all-time
            body["window"] = wq
        if self._outlier_info is not None:
            body["outlier"] = {
                k: round(v, 3) if isinstance(v, float) else v
                for k, v in self._outlier_info.items()
            }
        if eventslib.enabled():
            body["events"] = self.journal.stats()["recorded"]
        return web.json_response(body)

    def _update_gauges(self) -> None:
        """Refresh point-in-time gauges at scrape time (inflight requests,
        live sessions, KV bytes, worker-queue depth, span-ring state) —
        levels, not counters, so they are set rather than incremented."""
        m = self.metrics
        m.set_gauge("inflight", self.scheduler.inflight)
        store = getattr(self.executor, "sessions", None)
        try:
            m.set_gauge("sessions", len(store) if store is not None else 0)
        except TypeError:
            pass
        kvb = getattr(store, "kv_bytes", None)
        if callable(kvb):
            try:
                m.set_gauge("kv.bytes", kvb())
            except Exception:
                log.debug("kv_bytes gauge failed", exc_info=True)
        q = getattr(getattr(self.scheduler, "_pool", None), "_work_queue", None)
        if q is not None:
            try:
                m.set_gauge("queue.depth", q.qsize())
            except Exception:
                pass
        cb = self._cobatch_mean()
        if cb is not None:
            # mean sessions per co-batched device step (level, not a
            # counter — the window.cobatch histogram carries the shape)
            m.set_gauge("window.mean_cobatch", cb)
        ts = self.tracer.stats()
        m.set_gauge("trace.spans", ts["recorded"])
        m.set_gauge("trace.dropped", ts["dropped"])
        m.set_gauge("trace.buffered", ts["buffered"])
        # cumulative span-recording cost: perf/gate.check_span_overhead
        # warns when this exceeds 1% of cumulative stage.compute_ms
        m.set_gauge("trace.overhead_ms", ts["overhead_ms"])
        if eventslib.enabled():
            # device telemetry (HBM + KV occupancy; graceful CPU no-op)
            # and journal health — all gated on the events kill switch so
            # a disabled node's /metrics stays byte-identical to pre-PR
            devtellib.refresh_gauges(m, self.executor)
            es = self.journal.stats()
            m.set_gauge("events.count", es["recorded"])
            m.set_gauge("events.dropped", es["dropped"])
            m.set_gauge("events.buffered", es["buffered"])
            # budgeted by perf.gate alongside trace.overhead_ms (<=1% of
            # cumulative stage compute keeps always-on defensible)
            m.set_gauge("events.overhead_ms", es["overhead_ms"])
            # telemetry-plane costs ride the same budget: tsdb sampling
            # and canary bookkeeping must never silently eat the decode
            # wins (perf/gate.check_span_overhead)
            m.set_gauge("tsdb.overhead_ms", round(self.tsdb.overhead_ms, 3))
            # overload plane: drain state + the hedge budget's realized
            # extra-load fraction (the <=5% guarantee, observable) +
            # node-side retry-budget level (a dry bucket during an
            # incident = the containment working, not a failure)
            m.set_gauge("draining", 1.0 if self._draining else 0.0)
            m.set_gauge(
                "hedge.extra_frac", round(self.hedge_budget.extra_frac(), 4)
            )
            m.set_gauge(
                "retry.budget_tokens", round(self.retry_budget.tokens(), 2)
            )
            m.set_gauge(
                "replica.outlier", 1.0 if self._outlier_info else 0.0
            )
            if self.standby is not None:
                # crash-tolerance plane: shadow sessions held FOR peers
                # and their host-RAM cost (repl.lag_tokens — the primary-
                # side bounded-RPO gauge — refreshes in the repl tick).
                # Flag-gated like every repl.* series: a disabled node's
                # /metrics stays byte-identical to a build without them
                m.set_gauge(
                    "repl.standby_sessions", float(len(self.standby))
                )
                m.set_gauge(
                    "repl.standby_bytes", float(self.standby.bytes_held())
                )
            # trailing-window prefix-cache hit rate as a live gauge (the
            # gossiped `cachehit` field's /metrics face; rule input e.g.
            # `kv.cachehit > 0.1` for shared-prefix fleets). Only set
            # when the window saw prompt traffic — scrape-to-scrape the
            # last observed ratio may linger, but the gossip/fleet paths
            # use the windowed series directly
            ch = self._cachehit_frac()
            if ch is not None:
                m.set_gauge("kv.cachehit", ch)
            # short-window burn rates as live gauges (the SLO rules gate
            # on both windows; these feed dashboards/scrapes)
            for name, val in healthlib.burn_gauges(
                [self.tsdb.history()]
            ).items():
                m.set_gauge(name, val)
            if self.canary is not None:
                m.set_gauge(
                    "canary.overhead_ms", round(self.canary.overhead_ms, 3)
                )
            if self.prof is not None:
                # live-anatomy scan cost, budgeted by perf.gate next to
                # trace/events/tsdb/canary (<=1% of stage compute)
                m.set_gauge(
                    "prof.overhead_ms", round(self.prof.overhead_ms, 3)
                )
            lw = lockwatch.stats()
            if lw["checks"]:
                # lock-order sanitizer cost, same perf.gate 1% budget;
                # only exported while locks are actually watched so a
                # non-instrumented node's /metrics stays byte-identical
                m.set_gauge(
                    "lockwatch.overhead_ms", round(lw["overhead_ms"], 3)
                )

    async def handle_metrics(self, request: web.Request) -> web.Response:
        """GET /metrics — Prometheus text exposition of the node registry
        (counters, the gauges refreshed above, full histogram buckets)."""
        self._update_gauges()
        text = obs_export.prometheus_text(
            self.metrics, labels={"node": self.info.node_id}
        )
        return web.Response(
            body=text.encode(),
            headers={"Content-Type": obs_export.CONTENT_TYPE},
        )

    async def handle_metrics_history(self, request: web.Request) -> web.Response:
        """GET /metrics/history — the windowed tsdb rings as ONE JSON
        object (obs.tsdb schema: per-level counter/gauge rings + mergeable
        histogram bucket deltas). The pull surface of the fleet SLI
        pipeline: tools/collector --history fetches these per node and
        merges bucket deltas into fleet percentiles (obs.fleet) — never
        averages of averages."""
        self._update_gauges()
        self.tsdb.sample()
        return web.json_response(self.tsdb.history())

    async def handle_spans(self, request: web.Request) -> web.Response:
        """GET /spans — the live span ring as newline-delimited JSON
        (non-draining; the merge CLI's ad-hoc input for a running node)."""
        body = "\n".join(self.tracer.jsonl_lines()) + "\n"
        return web.Response(
            body=body.encode(),
            headers={"Content-Type": "application/x-ndjson"},
        )

    async def handle_events(self, request: web.Request) -> web.Response:
        """GET /events — the live event journal as newline-delimited JSON
        (non-draining; the postmortem CLI's ad-hoc input for a running
        node, mirroring /spans)."""
        body = "\n".join(self.journal.jsonl_lines()) + "\n"
        return web.Response(
            body=body.encode(),
            headers={"Content-Type": "application/x-ndjson"},
        )

    async def handle_stats(self, request: web.Request) -> web.Response:
        self._update_gauges()
        snap = self.metrics.snapshot()
        snap["trace"] = self.tracer.stats()
        proposed = snap["counters"].get("spec.proposed", 0)
        if proposed:
            # cumulative production acceptance rate — the speculative
            # engine's whole value proposition, observable in the field
            snap["spec"] = {
                "proposed": proposed,
                "accepted": snap["counters"].get("spec.accepted", 0),
                "accept_rate": snap["counters"].get("spec.accepted", 0) / proposed,
            }
        snap["dht"] = {str(k): v for k, v in self.dht.get_all(self.info.num_stages).items()}
        # overload-containment state: drain flag + both budgets' ledgers
        # (the bench's hedge-extra-load and retry-amplification evidence)
        snap["overload"] = {
            "draining": self._draining,
            "retry_budget": self.retry_budget.stats(),
            "hedge": self.hedge_budget.stats(),
        }
        if self.replicator is not None and self.standby is not None:
            # crash-tolerance ledgers (absent with --standby-repl off):
            # the failover bench reads promotions/frontiers from here
            snap["repl"] = {
                "sessions_tracked": len(self.replicator.state),
                "shipped_bytes": self.replicator.shipped_bytes,
                "ship_errors": self.replicator.ship_errors,
                "standby_sessions": len(self.standby),
                "standby_bytes": self.standby.bytes_held(),
            }
        stats_fn = getattr(self.executor, "stats", None)
        if callable(stats_fn):
            snap["executor"] = stats_fn()
        return web.json_response(snap)

    async def handle_profile(self, request: web.Request) -> web.Response:
        """POST {"action": "start"|"stop"|"window", ...} — on-demand
        jax.profiler trace (TensorBoard-loadable; SURVEY §5 gap).

        "window" is the fleet-coordinated form (tools/collector
        --capture): {"action": "window", "seconds": S, "capture_id": ID}
        starts a BOUNDED capture that stops itself after S seconds (S
        clamped to 60), tagged with the fleet-wide capture_id. The
        capture window is recorded as a `capture` span (so the
        clock-skew-corrected span merge lines wire spans up with the
        on-device trace), journaled, and the obs artifacts flush when it
        closes so the collector can assemble the bundle immediately.
        Start/stop/window all hold the shared capture lock for the whole
        trace, so live-anatomy ticks (obs.prof) never interleave.

        Opt-in only (--enable-profiling): an open profiler endpoint lets any
        peer degrade the node and fill its disk with traces (ADVICE r1)."""
        if not self.enable_profiling:
            return self._error_response(
                403, "profiling disabled (start the node with --enable-profiling)"
            )
        try:
            env = wire.unpack(await request.read())
            action = env["action"]
        except Exception as e:
            return self._error_response(400, f"bad profile request: {e}")
        loop = asyncio.get_running_loop()
        try:
            # off the event loop: start/stop do blocking work (first jax
            # import, mkdir, trace finalization) that would otherwise stall
            # the gossip heartbeat and get this node declared dead
            if action == "start":
                d = await loop.run_in_executor(
                    None, self.profiler.start, env.get("name") or env.get("dir")
                )
            elif action == "stop":
                d = await loop.run_in_executor(None, self.profiler.stop)
            elif action == "window":
                return await self._profile_window(env, loop)
            else:
                return self._error_response(400, f"unknown action {action!r}")
        except ValueError as e:
            return self._error_response(400, str(e))
        except RuntimeError as e:
            return self._error_response(409, str(e))
        return web.Response(body=wire.pack({"ok": True, "dir": d}))

    async def _profile_window(self, env, loop) -> web.Response:
        """One bounded, capture_id-tagged jax.profiler window."""
        try:
            seconds = min(max(float(env.get("seconds", 3.0)), 0.1), 60.0)
        except (TypeError, ValueError):
            return self._error_response(400, "bad seconds")
        capture_id = str(
            env.get("capture_id") or time.strftime("%Y%m%d-%H%M%S")
        )
        label = os.path.join(
            capture_id, self.info.node_id.replace(":", "_")
        )
        d = await loop.run_in_executor(None, self.profiler.start, label)
        t_start = tracelib.now()
        if eventslib.enabled():
            self.metrics.inc("prof.captures")
        self.journal.emit(
            "profile.capture", capture_id=capture_id,
            seconds=round(seconds, 3), dir=d,
        )

        async def _close() -> None:
            await asyncio.sleep(seconds)
            try:
                await loop.run_in_executor(None, self.profiler.stop)
            except Exception:
                log.exception("capture %s stop failed", capture_id)
            # the capture span: its [t0, t1] brackets the on-device trace,
            # so after the skew-corrected merge the wire spans of every
            # node line up against every node's device timeline
            self.tracer.record_span(
                "capture", "capture", t_start, tracelib.now(),
                attrs={"capture_id": capture_id, "dir": d},
            )
            self.journal.emit(
                "profile.capture_done", capture_id=capture_id, dir=d
            )
            self._flush_obs()

        self._capture_task = asyncio.create_task(_close())
        return web.Response(body=wire.pack({
            "ok": True, "dir": d, "capture_id": capture_id,
            "seconds": seconds,
        }))

    def _error_response(
        self, status: int, message: str, code: Optional[str] = None,
        retry_after: Optional[float] = None,
        resume_from: Optional[int] = None,
    ) -> web.Response:
        """Wire-packed error. `code` is machine-readable for clients:
        "session_state" (KV gone/out-of-order — a fresh session fixes it),
        "overflow" (KV budget exceeded — deterministic), "wrong_stage"
        (stale chain topology — deterministic), "deadline" (end-to-end
        budget spent — deterministic for THIS request), "busy"/"draining"
        (admission shed — transient; `retry_after` seconds, carried both
        in the body and as the standard Retry-After header, says when to
        come back). `resume_from` rides a session_state 409 when a
        standby holds the session's replicated KV prefix up to that
        position: a resume-aware client re-sends only the tail instead
        of restarting (old clients ignore the key and restart — today's
        path, by design)."""
        self.metrics.inc("errors")
        body: Dict[str, Any] = {"error": message}
        if code:
            body["code"] = code
        if resume_from is not None:
            body["resume_from"] = int(resume_from)
        headers = None
        if retry_after is not None:
            body["retry_after"] = retry_after
            # the HTTP header must be integer delta-seconds (RFC 7231);
            # the sub-second precision rides the wire body instead
            headers = {"Retry-After": str(max(0, math.ceil(retry_after)))}
        return web.Response(
            status=status, body=wire.pack(body), headers=headers
        )

    async def crash(self) -> None:
        """Fault-injection: die like a killed process — no DHT withdrawal
        (no tombstone gossip), sockets just close. Peers must detect the
        death via record-TTL expiry, exactly as with a real hard crash.
        Tests use this; production shutdown is stop()."""
        if self._sweep_task:
            self._sweep_task.cancel()
        if self._repl_task:
            self._repl_task.cancel()
        await self.balancer.stop()
        self.dht.kill()
        if self._http:
            await self._http.close()
        if self.chaos is not None:
            self.chaos.cancel_stalls()  # see stop(): unblock the cleanup
        if self._runner:
            try:
                # no graceful drain: cleanup() would wait for in-flight
                # handlers to answer — a real SIGKILL doesn't. Private attr
                # (no public setter post-construction); the constructor's
                # shutdown_timeout=5.0 bounds the drain even if a future
                # aiohttp renames it and this becomes a no-op.
                self._runner._shutdown_timeout = 0.0
            except Exception:
                pass
            await self._runner.cleanup()
        self.scheduler.shutdown()
        self._stopped.set()

    # ------------------------------------------------------------ migration

    async def change_stage(self, target: int) -> None:
        """Live migration to another stage: load its checkpoint (shared
        parts store), swap the executor, re-announce. In-flight requests
        finish on the old executor; new requests see the new stage."""
        if target == self.info.stage:
            return
        t0 = time.perf_counter()
        loop = asyncio.get_running_loop()
        new_executor = await loop.run_in_executor(None, self._load_executor, target)
        # eager warmup: pay the new stage's first jit compile NOW, off the
        # serving path, and time it — reassign -> ready-to-serve is the
        # latency half of BASELINE config 4 ("re-shards layer blocks
        # live"), exported as reshard.ms_to_serving. With a
        # persistent compilation cache (--compile-cache) the warm path
        # skips XLA re-compiles and this interval collapses to checkpoint
        # load + cache hits.
        await loop.run_in_executor(
            None, _warmup_executor, new_executor, self.journal
        )
        old_stage = self.info.stage
        old = self.executor
        self.executor = new_executor
        self._spec_engines.clear()  # built over the OLD executor's params
        self._spec_unsupported = False
        if self.standby is not None:
            # shadows and frontiers are STAGE-keyed: after the swap this
            # node can neither promote the old stage's shadows (wrong
            # layer slice — import would fail closed) nor extend its old
            # frontiers, and keeping them advertised under the NEW stage
            # map would misdirect peers' standby rescues — drop both
            self.standby.clear()
            self.replicator.state.clear()
        self.path_finder.planner = None  # planned from the OLD stage's view
        self.info.set_stage(target)
        self.tsdb.meta["stage"] = target  # fleet SLIs group by stage
        if self.prof is not None:
            # the swapped-in executor is a new anatomy target: old phase
            # scans (and the old stage's prior key) must not bleed over
            self.prof.reset_target()
        self.announce()
        self.metrics.inc("migrations")
        seconds = time.perf_counter() - t0
        # wider buckets than the hop histograms: a cold migration (no
        # --compile-cache) pays XLA recompiles and runs well past the
        # default 10 s cap — quantiles must not saturate to inf there
        self.metrics.observe(
            "reshard.ms_to_serving", seconds * 1e3,
            bounds_ms=[100, 250, 500, 1000, 2500, 5000, 10_000, 30_000,
                       60_000, 120_000, 300_000, 600_000],
        )
        self.journal.emit(
            "stage.migrate",
            **{"from": old_stage, "to": target,
               "ms_to_serving": round(seconds * 1e3, 1)},
        )
        self._health_cache = (0.0, None)  # stale stage in the cached verdict
        log.info(
            "node %s migrated to stage %d (ready to serve in %.2fs)",
            self.info.name, target, seconds,
        )
        # live handoff: ship the vacated executor's session KV to the old
        # stage's remaining replicas (off the critical path — the node is
        # already serving its new stage)
        await self._export_and_handoff(old, old_stage)
        del old
