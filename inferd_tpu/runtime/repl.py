"""Asynchronous standby KV replication — crash-tolerant sessions.

The swarm survives *graceful* exits (POST /drain hands resident KV to a
surviving replica token-exact), but an abrupt crash loses the KV and the
client pays a full restart + re-prefill. This module closes that hole
with an asynchronous session-replication plane:

  * the PRIMARY (the replica serving a session) periodically ships newly
    *completed* KV state past a per-session replication frontier to a
    gossip-chosen same-stage STANDBY — paged executors ship exactly the
    immutable full blocks past the frontier, dense executors ship slab
    deltas (the executors' `export_session_delta`, the incremental twin
    of the `export_sessions`/`import_session` handoff schema);
  * the standby accumulates deltas HOST-SIDE in a `StandbyStore` — no
    lane, no device KV, no executor state is touched until promotion, so
    shadow sessions cost RAM, never serving capacity;
  * on the primary's death, the standby PROMOTES: the accumulated
    payload imports through the ordinary `import_session` path (the
    fail-closed handoff validator), the client re-prefills only the
    tokens past the frontier (bounded RPO = the replication lag), and
    the generation continues token-exact — no full restart.

Strictly best-effort and OFF by default: with `--standby-repl` absent
the wire, gossip records, and /metrics are byte-identical to a build
without this module, and a stale or partial standby always degrades to
the client's ordinary restart path — staleness can cost recompute,
never a wrong token (greedy/seeded determinism + the executors'
replay-rollback protocol).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from inferd_tpu.utils import lockwatch

log = logging.getLogger(__name__)

#: wire key marking a replication delta's absolute start position; a
#: payload with start == 0 is exactly the handoff schema
START_KEY = "start"

#: shadow sessions not refreshed for this long are swept (a dead primary
#: either got promoted within seconds or the client restarted — either
#: way the stale bytes must not accumulate)
STANDBY_TTL_S = 300.0


class _Shadow:
    """One session's accumulated replica KV (host arrays). Deltas are
    kept as SEGMENT LISTS and concatenated once at promotion: appending
    by np.concatenate per tick would memcpy the whole accumulated
    buffer every delta — O(length^2) over a session's life."""

    __slots__ = ("ks", "vs", "length", "k_loc", "v_loc", "hi", "kv_dtype",
                 "stage", "last_update", "adapter")

    def __init__(self, stage: int):
        self.ks: List[np.ndarray] = []
        self.vs: List[np.ndarray] = []
        self.length = 0
        self.k_loc: Optional[np.ndarray] = None
        self.v_loc: Optional[np.ndarray] = None
        self.hi: Optional[int] = None
        self.kv_dtype: Optional[str] = None
        self.stage = stage
        self.last_update = time.monotonic()
        # tenant adapter the primary's deltas are stamped with (multi-
        # tenant LoRA): re-emitted at promotion so import_session rebinds
        # it — or declines on a registry-less/foreign-catalog standby
        self.adapter: Optional[str] = None


class StandbyStore:
    """Host-side accumulator of replicated session KV on the standby.

    apply() appends validated deltas at the exact frontier (anything
    else reports the length it HAS so the primary re-syncs from there);
    payload() reassembles the full `import_session` handoff dict at
    promotion time. Thread-safe; bounded by max_sessions (LRU on update
    time) and swept by TTL.
    """

    def __init__(self, max_sessions: int = 64, ttl_s: float = STANDBY_TTL_S):
        self.max_sessions = max_sessions
        self.ttl_s = ttl_s
        self._mu = lockwatch.make_lock("repl")
        self._shadows: Dict[str, _Shadow] = {}

    def __contains__(self, session_id: str) -> bool:
        with self._mu:
            return session_id in self._shadows

    def __len__(self) -> int:
        with self._mu:
            return len(self._shadows)

    def ids(self) -> List[str]:
        with self._mu:
            return list(self._shadows)

    def length(self, session_id: str) -> Optional[int]:
        """Replicated frontier of a shadow session (None = unknown)."""
        with self._mu:
            sh = self._shadows.get(session_id)
            return None if sh is None else sh.length

    def stage_of(self, session_id: str) -> Optional[int]:
        with self._mu:
            sh = self._shadows.get(session_id)
            return None if sh is None else sh.stage

    def apply(
        self, session_id: str, stage: int, payload: Dict[str, Any]
    ) -> Tuple[bool, int]:
        """Apply one replication delta. Returns (ok, have_length):
        ok=False means the delta didn't land (gap, malformed) and
        `have_length` is what the store holds — the primary resets its
        frontier there and re-ships. A delta at start == 0 always
        REPLACES the shadow (the primary re-synced from scratch)."""
        try:
            start = int(payload.get(START_KEY, 0))
            total = int(payload["length"])
            k = np.asarray(payload["k"])
            v = np.asarray(payload["v"])
        except Exception:
            return False, self.length(session_id) or 0
        if (
            k.ndim != 5 or v.shape != k.shape or k.shape[1] != 1
            or start < 0 or total <= start
            or k.shape[2] != total - start
        ):
            return False, self.length(session_id) or 0
        k_loc = payload.get("k_loc")
        v_loc = payload.get("v_loc")
        with self._mu:
            sh = self._shadows.get(session_id)
            if start == 0 or sh is None:
                if start != 0:
                    # mid-stream delta for an unknown session: ask for a
                    # full re-sync (the primary restarts its frontier)
                    return False, 0
                sh = _Shadow(stage)
                sh.ks, sh.vs = [k], [v]
                self._shadows[session_id] = sh
                self._evict_locked()
            else:
                if sh.length != start or sh.stage != stage:
                    return False, sh.length if sh.stage == stage else 0
                head = sh.ks[0]
                if k.shape[0] != head.shape[0] or k.shape[3:] != head.shape[3:]:
                    return False, sh.length
                if k.dtype != head.dtype:
                    return False, sh.length
                sh.ks.append(k)
                sh.vs.append(v)
            sh.length = total
            # rings ship WHOLE with every delta (every slot may be live);
            # the newest copy simply replaces the previous one
            if k_loc is not None:
                sh.k_loc = np.asarray(k_loc)
                sh.v_loc = np.asarray(v_loc)
                sh.hi = max(int(payload.get("hi", total)), total)
            kd = payload.get("kv_dtype")
            if kd is not None:
                sh.kv_dtype = str(kd)
            ad = payload.get("adapter")
            if ad is not None:
                sh.adapter = str(ad)
            sh.last_update = time.monotonic()
            return True, sh.length

    def payload(self, session_id: str) -> Optional[Dict[str, Any]]:
        """The full handoff-schema dict for promotion (import_session),
        or None. The import path's fail-closed validator is the real
        gate — this only reassembles bytes."""
        with self._mu:
            sh = self._shadows.get(session_id)
            if sh is None or not sh.ks or sh.length <= 0:
                return None
            # ONE concatenation, at promotion time (see _Shadow note)
            out: Dict[str, Any] = {
                "k": (
                    sh.ks[0] if len(sh.ks) == 1
                    else np.concatenate(sh.ks, axis=2)
                ),
                "v": (
                    sh.vs[0] if len(sh.vs) == 1
                    else np.concatenate(sh.vs, axis=2)
                ),
                "length": sh.length,
            }
            if sh.kv_dtype is not None:
                out["kv_dtype"] = sh.kv_dtype
            if sh.k_loc is not None:
                out["k_loc"] = sh.k_loc
                out["v_loc"] = sh.v_loc
                out["hi"] = sh.hi if sh.hi is not None else sh.length
            if sh.adapter is not None:
                out["adapter"] = sh.adapter
            return out

    def drop(self, session_id: str) -> None:
        with self._mu:
            self._shadows.pop(session_id, None)

    def clear(self) -> None:
        """Drop every shadow (a stage migration re-keys this node)."""
        with self._mu:
            self._shadows.clear()

    def sweep(self) -> int:
        """Drop shadows idle past the TTL; returns count dropped."""
        cutoff = time.monotonic() - self.ttl_s
        with self._mu:
            stale = [
                s for s, sh in self._shadows.items()
                if sh.last_update < cutoff
            ]
            for s in stale:
                del self._shadows[s]
            return len(stale)

    def bytes_held(self) -> int:
        with self._mu:
            total = 0
            for sh in self._shadows.values():
                for arr in (*sh.ks, *sh.vs, sh.k_loc, sh.v_loc):
                    total += int(getattr(arr, "nbytes", 0) or 0)
            return total

    def _evict_locked(self) -> None:
        while len(self._shadows) > self.max_sessions:
            oldest = min(
                self._shadows, key=lambda s: self._shadows[s].last_update
            )
            del self._shadows[oldest]


class SessionReplicator:
    """The primary-side half: tracks per-session replication frontiers
    and ships deltas to a sticky gossip-chosen standby.

    Pure policy + bookkeeping — the node supplies the I/O surfaces
    (`candidates_fn` returns ranked same-stage (node_id, record) pairs
    EXCLUDING this node, `ship_fn(node_id, record, body_dict)` POSTs one
    delta and returns the peer's {"ok", "length"|"have"} reply or raises
    on transport failure). Standby choice is sticky per session: a
    frontier is only meaningful against the standby that accumulated it,
    so a standby change resets the frontier to 0 (full re-ship).
    """

    def __init__(
        self,
        candidates_fn: Callable[[], List[Tuple[str, Dict[str, Any]]]],
    ):
        self.candidates_fn = candidates_fn
        # session_id -> (standby node_id, shipped frontier)
        self.state: Dict[str, Tuple[str, int]] = {}
        self.shipped_bytes = 0
        self.ship_errors = 0

    def lag_tokens(self, lengths: Dict[str, int]) -> int:
        """Sum over live sessions of tokens past the shipped frontier —
        the fleet's bounded-RPO gauge (`repl.lag_tokens`)."""
        total = 0
        for sid, n in lengths.items():
            _nid, f = self.state.get(sid, (None, 0))
            total += max(0, int(n) - f)
        return total

    def prune(self, live_sids) -> None:
        """Forget sessions no longer resident — SILENTLY. Residency loss
        is not session end: an LRU lane eviction or a live handoff
        destroys the local KV while the stream may well continue, and
        the standby's shadow is then exactly the crash protection the
        plane exists for (its TTL is the backstop). Explicit ends go
        through pop_standby (the node's /end_session drop notice)."""
        live = set(live_sids)
        for sid in [s for s in self.state if s not in live]:
            del self.state[sid]

    def pop_standby(self, sid: str) -> Optional[str]:
        """The sticky standby of an EXPLICITLY ended session (tracking
        removed) — the node sends it a drop notice so a finished 8k-ctx
        session's shadow doesn't sit in standby RAM, advertised, for
        the whole TTL. None when untracked."""
        nid_f = self.state.pop(sid, None)
        return None if nid_f is None else nid_f[0]

    def pick_standby(
        self, sid: str, cands: Optional[List[Tuple[str, Dict[str, Any]]]]
        = None, require_ada: bool = False,
    ) -> Optional[str]:
        """Sticky standby for `sid`: keep the current one while it is
        still a live candidate; otherwise the best-ranked same-stage
        peer (path_finder.ranked_nodes ordering: outlier-penalized,
        draining-excluded) that is not shedding. Anti-affinity (never
        the replica already serving the session) is the caller's
        candidates_fn excluding itself. `cands` lets plan() rank the
        stage map ONCE per tick instead of once per session.
        `require_ada` (tenant-adapter sessions): only adapter-CAPABLE
        peers — gossiped `ada` key, present even when empty — may hold
        the shadow; any other peer (old release, no registry) could
        never promote it, so shipping there silently voids the
        bounded-RPO promise. The sticky check uses the filtered set, so
        an existing shadow on a non-capable peer re-picks away."""
        if cands is None:
            cands = list(self.candidates_fn())
        if require_ada:
            cands = [(nid, rec) for nid, rec in cands if "ada" in rec]
        by_id = dict(cands)
        cur, _f = self.state.get(sid, (None, 0))
        if cur is not None and cur in by_id:
            return cur
        for nid, rec in cands:
            if not rec.get("shed"):
                return nid
        return cands[0][0] if cands else None

    def plan(
        self, lengths: Dict[str, int],
        adapters: Optional[Dict[str, str]] = None,
    ) -> List[Tuple[str, str, int]]:
        """[(session_id, standby_node_id, frontier)] for sessions with
        new KV to ship this tick. Mutates state only on record().
        `adapters` = {session_id: adapter name} for tenant sessions
        (pick_standby's require_ada filter)."""
        out = []
        cands = list(self.candidates_fn())
        for sid, n in sorted(lengths.items()):
            standby = self.pick_standby(
                sid, cands,
                require_ada=bool(adapters and adapters.get(sid)),
            )
            if standby is None:
                continue
            cur, frontier = self.state.get(sid, (None, 0))
            if cur != standby:
                frontier = 0  # new standby: its store starts empty
            if int(n) > frontier:
                out.append((sid, standby, frontier))
        return out

    def record(
        self, sid: str, standby: str, ok: bool,
        peer_length: Optional[int], body_bytes: int,
    ) -> None:
        """Fold one ship's outcome into the frontier state. A declined
        delta resets the frontier to whatever the peer reports holding
        (0 on garbage) so the next tick re-syncs from there."""
        if ok and peer_length is not None:
            self.state[sid] = (standby, int(peer_length))
            self.shipped_bytes += body_bytes
        else:
            self.ship_errors += 1
            self.state[sid] = (standby, max(0, int(peer_length or 0)))

    def note_standby_dead(self, sid: str) -> None:
        """Transport-level ship failure: forget the standby so the next
        tick re-picks (and re-ships from 0 — the dead peer's store is
        unreachable, so its accumulated frontier is worthless)."""
        self.ship_errors += 1
        self.state.pop(sid, None)
