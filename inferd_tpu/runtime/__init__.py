"""Node runtime (L2): stage executors, wire codec, async node server."""
