"""Mesh-backed whole-model executor: the north-star serving path.

The plain swarm topology hosts one stage per node and relays activations
over HTTP (runtime/node.py — the reference's design, petals/node.py:102-117,
upgraded). This executor is the TPU-native fusion BASELINE config 2 scores:
a node that owns N chips hosts the WHOLE model pipelined over an in-mesh
`pp` axis (parallel/infer.py) behind the SAME `/forward` surface — the
inter-stage hop becomes a `lax.ppermute` over ICI inside one jitted SPMD
program instead of a network round trip, and the swarm sees a single-stage
pipeline (is_first and is_last both true: tokens in, last-token logits out,
client-side sampling — the reference contract, client.py:204-287).

Sessions map to microbatch slots of the engine's persistent sharded KV
caches (one slot = one session's cache lane), with idle-TTL sweep and
slot refill on end_session — the per-session server-side cache story
(qwen3_server_module.py:220) carried over to the mesh.

process() is called from the node's worker thread pool; an internal lock
serializes device steps (the engine's donated caches admit one step at a
time). Different sessions interleave at step granularity.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from inferd_tpu.config import ModelConfig
from inferd_tpu.parallel import mesh as meshlib
from inferd_tpu.parallel.infer import PipelinedEngine
from inferd_tpu.runtime.spec_serving import SpecForkMiss, SpecServing

log = logging.getLogger(__name__)


class SlotSessions:
    """session_id -> cache slot, with idle TTL; free slots recycle.

    Exposes the same sweep()/__len__ surface the node's sweep loop expects
    (runtime/node.py:_sweep_loop). Locking contract: get/assign/drop are
    called by MeshExecutor UNDER its step lock; sweep() runs on the node's
    event loop, so it takes that same lock itself — otherwise a sweep could
    free a slot mid-step and hand it to a second session (cross-session KV
    corruption)."""

    def __init__(self, num_slots: int, ttl_s: float, lock: threading.Lock):
        self.ttl_s = ttl_s
        self._step_lock = lock
        self._slots: Dict[str, int] = {}
        self._last_used: Dict[str, float] = {}
        self._free = list(range(num_slots))

    def get(self, session_id: str) -> Optional[int]:
        slot = self._slots.get(session_id)
        if slot is not None:
            self._last_used[session_id] = time.monotonic()
        return slot

    def assign(self, session_id: str, protected=()) -> int:
        if not self._free:
            # evict the least-recently-used session (the stage executor's
            # SessionStore policy — a stale session loses its cache) that
            # is not protected (e.g. has a request in flight)
            victims = {s: t for s, t in self._last_used.items() if s not in protected}
            if not victims:
                raise BufferError("all slots busy with in-flight requests")
            oldest = min(victims, key=victims.get)
            self.drop(oldest)
        slot = self._free.pop()
        self._slots[session_id] = slot
        self._last_used[session_id] = time.monotonic()
        return slot

    def drop(self, session_id: str) -> None:
        slot = self.unmap(session_id)
        if slot is not None:
            self._free.append(slot)

    def unmap(self, session_id: str):
        """Remove the session->slot mapping WITHOUT freeing the slot (the
        caller defers the free until an in-flight request drains)."""
        self._last_used.pop(session_id, None)
        return self._slots.pop(session_id, None)

    def free_slot(self, slot: int) -> None:
        self._free.append(slot)

    def sweep(self) -> int:
        # Non-blocking: sweep() runs on the node's event loop, and a device
        # step (held under the same lock) can take seconds — blocking here
        # would freeze HTTP handling and gossip for that long. A busy round
        # just defers expiry to the next sweep.
        if not self._step_lock.acquire(blocking=False):
            return 0
        try:
            now = time.monotonic()
            stale = [s for s, t in self._last_used.items() if now - t > self.ttl_s]
            for s in stale:
                self.drop(s)
            return len(stale)
        finally:
            self._step_lock.release()

    def __len__(self) -> int:
        return len(self._slots)

    def __contains__(self, session_id: str) -> bool:
        return session_id in self._slots

    def ids(self):
        """Live session ids (gossip session-location advertising). Lock-free
        point-in-time key copy: callers (announce) tolerate staleness, and
        taking the step lock here could block the event loop for a whole
        device step."""
        return list(self._slots)


class MeshExecutor(SpecServing):
    """Whole-model stage executor pipelined over an in-mesh pp axis."""

    def __init__(
        self,
        cfg: ModelConfig,
        params: Dict[str, Any],
        plan: meshlib.MeshPlan,
        num_slots: int = 8,
        max_len: int = 4096,
        session_ttl_s: float = 600.0,
        devices=None,
        window_ms: float = 3.0,
        spec_draft_layers: int = 0,
        spec_k: int = 4,
    ):
        import jax

        devs = list(devices) if devices is not None else jax.devices()
        if plan.num_devices > len(devs):
            raise ValueError(
                f"mesh plan needs {plan.num_devices} devices, have {len(devs)}"
            )
        mesh = meshlib.make_mesh(plan, devs[: plan.num_devices])
        self.cfg = cfg
        self.plan = plan
        self.max_len = max_len
        self.engine = PipelinedEngine(
            cfg, params, mesh,
            num_microbatches=num_slots, batch=1, max_len=max_len,
        )
        # Sliding-window models run O(window) RING storage on their sliding
        # layers whenever every pp rank's layer slice starts on an even
        # global index (parallel.infer.ring_split_ok — then the rank-local
        # sliding/global pattern is one STATIC program on all ranks). Only
        # the odd-layers-per-rank niche (e.g. Gemma-2's 26 layers at pp=2)
        # keeps the uniform mask-only fallback; observable, not silent.
        self.kv_window_fallback = bool(
            cfg.sliding_window and not self.engine.ring_active
        )
        if self.kv_window_fallback:
            log.warning(
                "mesh executor: sliding-window model %s uses uniform KV "
                "(O(context) reads on sliding layers: %d layers per pp "
                "rank is odd, so the ring layout cannot be one SPMD "
                "program — pick a pp that divides the layers evenly)",
                cfg.name, cfg.num_layers // plan.pp,
            )
        self._lock = threading.Lock()
        self.sessions = SlotSessions(num_slots, session_ttl_s, self._lock)
        # host mirror of each session's cache length (device sync per step
        # would stall the pipeline)
        self._session_len: Dict[str, int] = {}
        # ring-KV replay safety (mirrors the stage executor): high-water
        # mark of positions ever written per session — a replay rollback or
        # a fork truncation is exact only while (hi - target) stays under
        # RING_MARGIN (core.cache aliasing invariant). Guarded by _lock.
        self._ring_hi: Dict[str, int] = {}
        self._inflight: Dict[str, int] = {}  # session -> active request count
        self._dying: Dict[int, str] = {}  # slot -> ended session awaiting drain
        # windowed decode coalescing: the pipeline pass natively interleaves
        # all MB slots, so decode steps of sessions co-arriving within the
        # window share ONE pass instead of one traversal each
        from inferd_tpu.runtime.window import WindowedBatcher

        self._batcher = WindowedBatcher(
            window_s=window_ms / 1e3,
            run_batch=self._run_decode_batch,
            co_possible=lambda: len(self.sessions) > 1,
        )
        self._spec_window_s = window_ms / 1e3
        # in-mesh lane... slot speculation (parallel.infer.MeshSpecRunner):
        # None until enabled. Structurally impossible configs (ring margin,
        # layer counts) log + serve without.
        self._spec = None
        if spec_draft_layers > 0:
            try:
                self.enable_spec(spec_draft_layers, spec_k, params)
            except (ValueError, RuntimeError) as e:
                log.warning("mesh speculation disabled (%s); serving without", e)

    # -- slot-batched speculative serving (parallel.infer.MeshSpecRunner) ----
    #
    # Mirrors runtime/batch_executor's lane speculation with slots in place
    # of lanes: a speculating session is an ordinary microbatch slot, spec
    # rounds interleave with regular /forward decode flushes under the same
    # step lock, and EVERY live session is capped at max_len - (k+1) so the
    # verify chunk's K+1 frontier writes can never clamp into valid KV
    # (core.spec_batch headroom contract; dead slots' garbage writes are
    # self-contained). The session-level drive is the shared SpecServing
    # mixin; the structural difference here: cache lengths advance IN-JIT
    # (PipelinedCaches.lengths), so the flush syncs host mirrors from the
    # returned n_new instead of advancing device state.

    @property
    def _spec_mu(self):
        return self._lock

    def _spec_session_slot(self, session_id):
        return self.sessions.get(session_id)

    def _spec_session_len(self, session_id, slot):
        return self._session_len.get(session_id, 0)

    def _spec_free_slot(self, session_id, slot):
        self.sessions.free_slot(slot)
        self._session_len.pop(session_id, None)
        self._ring_hi.pop(session_id, None)

    def _spec_drop(self, session_id):
        slot = self.sessions.unmap(session_id)
        if slot is None:
            return
        self._batcher.invalidate(
            lambda payload, _s=slot: payload[0] == _s,
            ValueError(f"session {session_id} closed"),
        )
        if self._inflight.get(session_id):
            self._dying[slot] = session_id
        else:
            self._spec_free_slot(session_id, slot)

    def _spec_new_runner(self, sampling):
        from inferd_tpu.parallel.infer import MeshSpecRunner

        return MeshSpecRunner(self.engine, sampling)

    def _spec_plain_submit(self, slot, last_tok, session_id):
        return self._batcher.submit((slot, last_tok, session_id))

    def enable_spec(self, draft_layers: int, k: int, raw_params) -> None:
        self.engine.enable_spec(draft_layers, k, raw_params)
        self._spec = self._spec_init(k, self.engine.mb)

    def spec_open(self, session_id: str, prompt_ids, sampling, seed: int = 0,
                  parent: "str | None" = None, pin_len: int = 0,
                  prefix_logits=None, want_lp: bool = False):
        """Claim a slot, prefill target + draft, return the first token.
        The session stays in-flight until spec_close (idle slots between
        rounds must not be evicted). Raises BufferError on budget/slots.
        `parent`/`pin_len`/`prefix_logits` compose speculation with prefix
        caching exactly like batch_executor.spec_open (fork the parent
        slot's prefix KV, target-prefill the suffix, draft-prefill the
        whole prompt); a fork miss raises SpecForkMiss."""
        import jax
        from inferd_tpu.core.generate import bucket_len

        sp = self._spec
        if sp is None:
            raise RuntimeError("speculation not enabled on this executor")
        n = len(prompt_ids)
        if n + 1 > self.cap:
            raise BufferError(
                f"prompt of {n} exceeds spec-capped capacity {self.cap}"
            )
        runner, batcher, rkey = self._spec_runner(sampling)
        toks = np.asarray([list(prompt_ids)], np.int32)
        forked = False
        if parent is not None and 0 < pin_len <= n:
            # fork_session takes self._lock internally: call it first
            if not self.fork_session(session_id, parent, pin_len):
                raise SpecForkMiss(f"prefix fork from {parent} missed")
            forked = True
        with self._lock:
            if self._inflight.get(session_id):
                raise ValueError(f"session {session_id}: concurrent request")
            if forked:
                slot = self.sessions.get(session_id)
                if slot is None:  # evicted in the unlocked window
                    raise SpecForkMiss("forked slot evicted before open")
            else:
                slot = self.sessions.assign(
                    session_id, protected=set(self._inflight)
                )
                self._session_len = {
                    s: l for s, l in self._session_len.items()
                    if s in self.sessions
                }
                self._ring_hi = {
                    s: h for s, h in self._ring_hi.items()
                    if s in self.sessions
                }
                self._ring_hi.pop(session_id, None)
            self._inflight[session_id] = 1
            try:
                start = pin_len if forked else 0
                suffix = toks[:, start:]
                if suffix.shape[1]:
                    logits = self.engine.step_slot(
                        slot, suffix, n - start, reset=not forked,
                        start_pos=start,
                    )
                else:
                    if prefix_logits is None:
                        raise SpecForkMiss(
                            "prompt == pinned prefix but no stored logits"
                        )
                    logits = np.asarray(prefix_logits)[None]
                b = min(bucket_len(n), self.max_len)
                padded = np.zeros((1, b), np.int32)
                padded[0, :n] = toks[0]
                runner.draft_prefill(padded, slot, 0, n)
                self._session_len[session_id] = n
                if self.engine.ring_active:
                    self._ring_hi[session_id] = max(
                        self._ring_hi.get(session_id, 0), n
                    )
                sp["dlens"][slot] = n
                sp["sid"][session_id] = (runner, batcher, rkey, want_lp)
                key, sub = jax.random.split(jax.random.PRNGKey(seed))
                sp["keys"][session_id] = key
                sp["count"][rkey] = sp["count"].get(rkey, 0) + 1
            except Exception:
                self._inflight.pop(session_id, None)
                self.sessions.drop(session_id)
                self._session_len.pop(session_id, None)
                raise
        first = runner.first_token(logits[0], sub)
        first_lp = runner.row_lp(logits[0], first) if want_lp else None
        return first, first_lp

    def _run_spec_batch(self, runner, entries) -> None:
        """Spec flush: ONE SPMD round advances every waiting slot."""
        sp = self._spec
        MB = self.engine.mb
        with self._lock:
            active = np.zeros((MB,), bool)
            last = np.zeros((MB,), np.int32)
            catch = np.zeros((MB,), np.int32)
            catch_mask = np.zeros((MB,), bool)
            keys = np.zeros((MB, 2), np.uint32)
            sampled = runner.sampling.temperature > 0.0
            wants = {}
            for e in entries:
                slot, sid, lt, pt, sub = e.payload
                active[slot] = True
                last[slot] = lt
                ent = sp["sid"].get(sid)
                wants[slot] = bool(ent and ent[3])
                if sp["dlens"][slot] < self._session_len.get(sid, 0):
                    catch[slot] = pt
                    catch_mask[slot] = True
                if sampled:
                    keys[slot] = sub
            dlens = np.asarray(sp["dlens"], np.int32)
            want_flush = any(wants.values())
            res = runner.run_round(
                last, catch, catch_mask, dlens, active,
                keys if sampled else None, want_lp=want_flush,
            )
            if want_flush:
                toks, n_new, lps, tis, tls = res
            else:
                toks, n_new = res
            for e in entries:
                slot, sid, _, _, _ = e.payload
                n = int(n_new[slot])
                old = self._session_len.get(sid, 0)
                self._session_len[sid] = old + n
                sp["dlens"][slot] = old + min(n, runner.k)
                if self.engine.ring_active:
                    self._ring_hi[sid] = max(
                        self._ring_hi.get(sid, 0), old + runner.k + 1
                    )
                e.result = self._spec_entry_result(
                    wants.get(slot), toks[slot], n,
                    lps[slot] if want_flush else None,
                    tis[slot] if want_flush else None,
                    tls[slot] if want_flush else None,
                )

    # -- node executor surface (same contract as Qwen3StageExecutor) --------

    def process(self, session_id: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        """payload: {"tokens": int32 [1, S], "start_pos": int, "real_len"}.
        The mesh node is first AND last stage, so the reply always carries
        last-real-token logits [1, V]."""
        toks = np.asarray(payload["tokens"], dtype=np.int32)
        if toks.ndim != 2 or toks.shape[0] != 1:
            raise ValueError(f"mesh stage expects tokens [1, S], got {toks.shape}")
        start_pos = int(payload.get("start_pos", 0))
        real_len = int(payload.get("real_len", toks.shape[1]))

        with self._lock:
            if self._inflight.get(session_id):
                # a duplicate/replayed request racing the original would
                # pass the frontier check and double-advance the slot
                raise ValueError(
                    f"session {session_id}: concurrent request (one step at "
                    "a time per session)"
                )
            slot = self.sessions.get(session_id)
            new = slot is None
            if new:
                if start_pos != 0:
                    raise ValueError(
                        f"session {session_id}: unknown session resumed at "
                        f"start_pos {start_pos} (cache evicted or node restarted)"
                    )
                slot = self.sessions.assign(
                    session_id, protected=set(self._inflight)
                )
                # assign() may have evicted a session; drop orphaned lengths
                self._session_len = {
                    s: l for s, l in self._session_len.items() if s in self.sessions
                }
                self._ring_hi = {
                    s: h for s, h in self._ring_hi.items() if s in self.sessions
                }
                # a leftover mark under this id belongs to a previous
                # session's rings and would wrongly reject legal replays
                self._ring_hi.pop(session_id, None)
            else:
                have = self._session_len.get(session_id, 0)
                if start_pos == 0 and have:
                    # session restart under the same id: reset the slot
                    self._session_len[session_id] = 0
                    self._ring_hi.pop(session_id, None)
                    have = 0
                    new = True  # step with reset
                if start_pos + real_len > self.cap:
                    # checked BEFORE the rollback mutation (a rejected
                    # oversized replay must not leave the slot rolled back).
                    # `cap` < max_len while speculation is enabled
                    # (verify-chunk headroom on every live session).
                    raise BufferError(
                        f"session {session_id}: KV overflow "
                        f"({start_pos}+{real_len} > {self.cap})"
                    )
                if start_pos != have:
                    if 0 < start_pos < have:
                        # deterministic chunk REPLAY (a client re-sent after
                        # a lost response): roll the slot's frontier back
                        # and recompute — identical KV (deterministic
                        # forward). Ring storage bounds the depth: past the
                        # margin the rings have already overwritten the
                        # rolled-back positions (same guard as the stage
                        # executor's replay path); uniform layouts accept
                        # any depth.
                        if self.engine.ring_active:
                            from inferd_tpu.core.cache import RING_MARGIN

                            hi = max(self._ring_hi.get(session_id, 0), have)
                            if hi - start_pos > RING_MARGIN:
                                raise ValueError(
                                    f"session {session_id}: replay rollback "
                                    f"to {start_pos} exceeds the ring margin "
                                    f"(high-water mark {hi})"
                                )
                        self.engine.set_slot_length(slot, start_pos)
                        self._session_len[session_id] = start_pos
                    else:
                        raise ValueError(
                            f"session {session_id}: start_pos {start_pos} != "
                            f"cache length {have} (out-of-order chunk)"
                        )
            if start_pos + real_len > self.cap:
                raise BufferError(
                    f"session {session_id}: KV overflow "
                    f"({start_pos}+{real_len} > {self.cap})"
                )
            self._inflight[session_id] = 1

        try:
            if real_len == 1 and start_pos > 0:
                row = self._batcher.submit((slot, int(toks[0, 0]), session_id))
                logits = row[None, :]
            elif (
                start_pos == 0 and real_len > 1 and self.engine.sp_active
            ):
                # sequence-parallel prefill: the prompt shards over the sp
                # axis (ring attention per layer), K/V gathers into the
                # slot's cache — each chip pays 1/sp of the prefill; decode
                # continues on the standard pass token-exact. Chunked
                # continuations (start_pos > 0) use the standard path.
                with self._lock:
                    logits = self.engine.sp_prefill_slot(slot, toks, real_len)
                    self._session_len[session_id] = real_len
            else:
                with self._lock:
                    logits = self.engine.step_slot(
                        slot, toks, real_len, reset=new, start_pos=start_pos
                    )
                    self._session_len[session_id] = start_pos + real_len
                    if self.engine.ring_active:
                        self._ring_hi[session_id] = max(
                            self._ring_hi.get(session_id, 0),
                            start_pos + real_len,
                        )
        finally:
            with self._lock:
                self._inflight.pop(session_id, None)
                if self._dying.get(slot) == session_id:  # ended mid-request
                    del self._dying[slot]
                    self._session_len.pop(session_id, None)
                    self._ring_hi.pop(session_id, None)
                    self.sessions.free_slot(slot)

        return {
            "logits": logits,
            "real_len": real_len,
            "start_pos": start_pos,
        }

    def export_sessions(self, only: "str | None" = None):
        """Snapshot live sessions' slot KV for migration/shutdown handoff
        (stage-executor payload schema; layer axis reassembled across
        pp/tp ranks by PipelinedEngine.export_slot) — so _export_and_handoff
        and /import_session work unchanged for --mesh replicas. `only`
        exports a single session (the prefill->decode handoff path)."""
        from inferd_tpu.runtime import handoff

        out = []
        with self._lock:
            pairs = [
                (sid, self.sessions.get(sid))
                for sid in self.sessions.ids()
                if only is None or sid == only
            ]
            for sid, slot in pairs:
                if slot is None:
                    continue
                k, v, ln, kl, vl = self.engine.export_slot(slot)
                if ln <= 0:
                    continue
                hi = max(self._ring_hi.get(sid, 0), ln) if kl is not None else None
                out.append((sid, handoff.encode(
                    np.ascontiguousarray(k[:, :, :ln]),
                    np.ascontiguousarray(v[:, :, :ln]), ln,
                    k_loc=None if kl is None else np.ascontiguousarray(kl),
                    v_loc=None if vl is None else np.ascontiguousarray(vl),
                    hi=hi,
                )))
        return out

    def import_session(self, session_id: str, payload: Dict[str, Any]) -> bool:
        """Adopt a migrated session into a free slot (same-model mesh
        replicas — possibly a DIFFERENT pp/tp split: import_slot re-shards
        onto this mesh). Shape mismatches reject cleanly."""
        from inferd_tpu.runtime import handoff

        if payload.get("adapter") is not None:
            # a tenant session's KV was built with its adapter; the mesh
            # executor has no registry (--adapters is lane-executor-only)
            # so adopting would silently resume on the base weights —
            # decline and let it land on a registry replica or restart
            return False
        dec = handoff.decode(
            payload, self.cfg, self.cfg.num_layers, 0, self.cap,
            want_ring=self.engine.ring_active,
        )
        if dec is None:
            return False
        k, v, n = dec["k"], dec["v"], dec["n"]
        with self._lock:
            if session_id in self.sessions:
                return False
            try:
                slot = self.sessions.assign(
                    session_id, protected=set(self._inflight)
                )
            except BufferError:
                return False
            # assign() may have evicted a session; drop orphaned lengths
            # (same bookkeeping as process() and fork_session())
            self._session_len = {
                s: l for s, l in self._session_len.items() if s in self.sessions
            }
            self._ring_hi = {
                s: h for s, h in self._ring_hi.items() if s in self.sessions
            }
            try:
                self.engine.import_slot(
                    slot, k, v, n, k_loc=dec["k_loc"], v_loc=dec["v_loc"]
                )
            except (ValueError, BufferError):
                self.sessions.drop(session_id)
                return False
            self._session_len[session_id] = n
            if self.engine.ring_active:
                # the source's rings' stale slots reach ITS high-water mark
                # — the replay guard here must inherit the true value
                self._ring_hi[session_id] = dec["hi"]
        return True

    def stats(self):
        """Coalescing effectiveness for /stats."""
        return {
            "mode": "mesh",
            "pp": self.plan.pp,
            "slots": self.engine.mb,
            "sessions": len(self.sessions),
            "kv_window_fallback": self.kv_window_fallback,
            **self._batcher.stats(),
            **self.spec_stats(),
        }

    def _run_decode_batch(self, entries) -> None:
        """Flush callback (runtime/window.py): ONE pipeline pass advances
        every waiting slot together."""
        with self._lock:
            out = self.engine.step_slots(
                {e.payload[0]: e.payload[1] for e in entries}
            )
            for e in entries:
                slot, _tok, sid = e.payload
                if self._dying.get(slot) != sid:  # ended-mid-flush: the
                    # _dying drain discards the mirror anyway; everyone else
                    # advances in lockstep with the device-side length
                    self._session_len[sid] = self._session_len.get(sid, 0) + 1
                    if self.engine.ring_active:
                        self._ring_hi[sid] = max(
                            self._ring_hi.get(sid, 0), self._session_len[sid]
                        )
                e.result = out[slot]

    def fork_session(
        self, new_session_id: str, parent_session_id: str, prefix_len: int
    ) -> bool:
        """Seed a new session's slot from the parent slot's KV prefix
        (prefix caching on the in-mesh pipelined path — the copy is
        shard-local on every pp rank). False on any miss; the caller falls
        back to a full prefill."""
        if prefix_len <= 0:
            return False
        with self._lock:
            pslot = self.sessions.get(parent_session_id)
            if (
                pslot is None
                or self._session_len.get(parent_session_id, 0) < prefix_len
                or new_session_id in self.sessions
            ):
                return False
            if self.engine.ring_active:
                # ring fork-truncation margin (core.cache aliasing
                # invariant): the child's rings carry parent data up to the
                # parent's HIGH-WATER mark; slots past prefix_len stay
                # structurally outside every window only while the
                # truncation depth is under the margin
                from inferd_tpu.core.cache import RING_MARGIN

                phi = max(
                    self._ring_hi.get(parent_session_id, 0),
                    self._session_len.get(parent_session_id, 0),
                )
                if phi - prefix_len > RING_MARGIN:
                    return False
            try:
                slot = self.sessions.assign(
                    new_session_id,
                    protected=set(self._inflight) | {parent_session_id},
                )
            except BufferError:
                return False
            # assign() may have evicted a session; drop orphaned lengths
            # AND ring marks (fork is the spec path's common admission —
            # without the _ring_hi prune a pinned-heavy ring workload
            # accumulates dead sessions' marks)
            self._session_len = {
                s: l for s, l in self._session_len.items() if s in self.sessions
            }
            self._ring_hi = {
                s: h for s, h in self._ring_hi.items() if s in self.sessions
            }
            self.engine.fork_slot(pslot, slot, prefix_len)
            self._session_len[new_session_id] = prefix_len
            if self.engine.ring_active:
                # the child's rings inherit the PARENT's stale frontier
                self._ring_hi[new_session_id] = max(
                    self._ring_hi.get(parent_session_id, 0),
                    self._session_len.get(parent_session_id, 0),
                )
        return True

    def end_session(self, session_id: str) -> None:
        with self._lock:
            slot = self.sessions.unmap(session_id)
            if slot is None:
                return
            # fail-fast decode entries still waiting in the window; a
            # request mid-device-step defers the slot free until it drains
            self._batcher.invalidate(
                lambda payload, _s=slot: payload[0] == _s,
                ValueError(f"session {session_id} ended mid-request"),
            )
            if self._inflight.get(session_id):
                self._dying[slot] = session_id
            else:
                self.sessions.free_slot(slot)
                self._session_len.pop(session_id, None)
                self._ring_hi.pop(session_id, None)
