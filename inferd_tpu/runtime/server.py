"""Node bootstrap CLI: `python -m inferd_tpu.runtime.server`.

Capability parity with /root/reference/petals/run_node.py:40-86 (load the
cluster yaml, resolve identity from env/flags, start DHT then node, block
forever). Same environment contract — INITIAL_STAGE, NODE_NAME,
BOOTSTRAP_NODES ("host:port,host:port"), NODE_IP — plus flags for
everything, a --device {tpu,cpu} selector behind the same entrypoint
(BASELINE.json north star), and a --backend counter mode for model-free
swarm testing.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import socket
from typing import List, Tuple

from inferd_tpu.config import get_config
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.parallel.stages import Manifest
from inferd_tpu.runtime.node import Node, NodeInfo


def get_own_ip() -> str:
    """Best-effort routable IP (reference run_node.py:9-13)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except Exception:
        return "127.0.0.1"
    finally:
        s.close()


def parse_bootstrap(text: str) -> List[Tuple[str, int]]:
    out = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        host, port = part.rsplit(":", 1)
        out.append((host, int(port)))
    return out


def build_node(args) -> Node:
    if args.device:
        os.environ.setdefault("JAX_PLATFORMS", args.device)
    manifest = Manifest.from_yaml(args.manifest) if args.manifest else None

    name = args.name or os.environ.get("NODE_NAME") or f"node-{os.getpid()}"
    stage_env = os.environ.get("INITIAL_STAGE")
    stage = args.stage if args.stage is not None else int(stage_env or 0)
    host = args.host or os.environ.get("NODE_IP") or get_own_ip()
    bootstrap = parse_bootstrap(args.bootstrap or os.environ.get("BOOTSTRAP_NODES", ""))

    if manifest is not None:
        cfg = manifest.config
        num_stages = manifest.num_stages
        model_name = manifest.model_name
    else:
        cfg = get_config(args.model)
        num_stages = args.num_stages
        model_name = args.model

    info = NodeInfo(
        name=name, host=host, port=args.port, stage=stage,
        num_stages=num_stages, capacity=args.capacity, model_name=model_name,
    )
    dht = SwarmDHT(
        node_id=info.node_id, port=args.dht_port, bootstrap=bootstrap, host=host
    )
    return Node(
        info, cfg, args.parts, dht,
        backend=args.backend, max_len=args.max_len,
        rebalance_period_s=args.rebalance_period,
    )


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--manifest", help="cluster yaml (model + stage table)")
    ap.add_argument("--model", default="qwen3-0.6b", help="model preset (no manifest)")
    ap.add_argument("--num-stages", type=int, default=2)
    ap.add_argument("--parts", default="model_parts", help="stage checkpoint dir")
    ap.add_argument("--stage", type=int, default=None, help="initial stage (env INITIAL_STAGE)")
    ap.add_argument("--name", default=None, help="node name (env NODE_NAME)")
    ap.add_argument("--host", default=None, help="bind/advertise ip (env NODE_IP)")
    ap.add_argument("--port", type=int, default=6050, help="http port")
    ap.add_argument("--dht-port", type=int, default=7050, help="gossip udp port")
    ap.add_argument("--bootstrap", default=None, help="host:port,... (env BOOTSTRAP_NODES)")
    ap.add_argument("--capacity", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=4096)
    ap.add_argument("--rebalance-period", type=float, default=10.0)
    ap.add_argument("--backend", choices=["qwen3", "counter"], default="qwen3")
    ap.add_argument("--device", choices=["tpu", "cpu", ""], default="",
                    help="JAX platform override (tpu = default axon/libtpu)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    node = build_node(args)

    async def run():
        await node.start()
        try:
            await asyncio.Event().wait()
        finally:
            await node.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
