"""First-arrival-flushes micro-batch window (thread-safe, executor-agnostic).

Shared by the continuous-batching executor (runtime/batch_executor.py) and
the in-mesh pipelined executor (runtime/mesh_executor.py): decode requests
from concurrent sessions that arrive within a short window run as ONE
device step. The first arriving thread becomes the flusher — it waits
`window_s` for co-arrivals (skipped when none are possible), then calls the
executor's `run_batch` callback with every pending entry; co-arrived
threads block on their entry until the flusher distributes results.

The executor's `run_batch(entries)` must:
  * acquire its own device lock (the batcher holds no locks while calling);
  * set `entry.result` for each entry it serves;
errors raised by run_batch are propagated to every entry in the batch.

`invalidate(pred, error)` lets session teardown fail-fast entries that are
still waiting in the window (never started), so a freed lane/slot can be
reused without a stale write racing its new owner.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional


class Entry:
    __slots__ = ("payload", "event", "result", "error")

    def __init__(self, payload: Any):
        self.payload = payload
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[Exception] = None


class WindowedBatcher:
    def __init__(
        self,
        window_s: float,
        run_batch: Callable[[List[Entry]], None],
        co_possible: Callable[[], bool],
        wait_timeout_s: float = 120.0,
    ):
        self.window_s = window_s
        self._run_batch = run_batch
        self._co_possible = co_possible
        self._wait_timeout_s = wait_timeout_s
        self._mu = threading.Lock()
        self._pending: List[Entry] = []
        self._flusher_active = False
        self.n_steps = 0  # flushed batches
        self.n_served = 0  # entries served across those batches

    def submit(self, payload: Any) -> Any:
        entry = Entry(payload)
        with self._mu:
            self._pending.append(entry)
            i_flush = not self._flusher_active
            if i_flush:
                self._flusher_active = True
            wait = self._co_possible()

        if not i_flush:
            entry.event.wait(timeout=self._wait_timeout_s)
            if entry.error is not None:
                raise entry.error
            if not entry.event.is_set():
                raise TimeoutError("batched decode flusher never completed")
            return entry.result

        if wait:
            time.sleep(self.window_s)
        with self._mu:
            batch, self._pending = self._pending, []
            self._flusher_active = False
        # entries invalidated between swap and here already have error set;
        # run the rest
        live = [e for e in batch if e.error is None]
        try:
            if live:
                self._run_batch(live)
                self.n_steps += 1
                self.n_served += len(live)
        except Exception as exc:
            for e in live:
                e.error = exc
                e.event.set()
            raise
        for e in live:
            e.event.set()
        if entry.error is not None:
            raise entry.error
        return entry.result

    def stats(self) -> dict:
        """Coalescing effectiveness counters (shared by both executors)."""
        return {
            "batched_steps": self.n_steps,
            "batched_tokens": self.n_served,
            "mean_batch": round(self.n_served / self.n_steps, 3)
            if self.n_steps
            else 0.0,
        }

    def invalidate(self, pred: Callable[[Any], bool], error: Exception) -> None:
        """Fail-fast waiting entries whose payload matches `pred` (they have
        not started executing — entries already swapped into a running
        flush are the executor's responsibility via its in-flight
        accounting)."""
        with self._mu:
            still = []
            for e in self._pending:
                if pred(e.payload):
                    e.error = error
                    e.event.set()
                else:
                    still.append(e)
            self._pending[:] = still
