"""First-arrival-flushes micro-batch window (thread-safe, executor-agnostic).

Shared by the continuous-batching executor (runtime/batch_executor.py) and
the in-mesh pipelined executor (runtime/mesh_executor.py): decode requests
from concurrent sessions that arrive within a short window run as ONE
device step. The first arriving thread becomes the flusher — it waits
`window_s` for co-arrivals (skipped when none are possible), then calls the
executor's `run_batch` callback with every pending entry; co-arrived
threads block on their entry until the flusher distributes results.

The executor's `run_batch(entries)` must:
  * acquire its own device lock (the batcher holds no locks while calling);
  * set `entry.result` for each entry it serves;
errors raised by run_batch are propagated to every entry in the batch.

`invalidate(pred, error)` lets session teardown fail-fast entries that are
still waiting in the window (never started), so a freed lane/slot can be
reused without a stale write racing its new owner.

Two opt-in modes power STAGE-level continuous batching (runtime/node +
runtime/stage_batch — see docs/SERVING.md):
  * `swap_in_run`: the flusher passes run_batch an EMPTY list and the
    callback pulls the batch itself via `drain_pending()` once it holds
    the device — entries arriving mid-step join the next step instead of
    fragmenting into mini-batches queued on the device lock;
  * `gang_target`: the window wait ends early once every live idle
    session's entry is pending, which merges phase-offset session
    cohorts into one lockstep co-batch and lets the window be sized
    generously without charging steady-state latency.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional  # noqa: F401

from inferd_tpu.utils import lockwatch


class Entry:
    __slots__ = ("payload", "event", "result", "error")

    def __init__(self, payload: Any):
        self.payload = payload
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[Exception] = None


class WindowedBatcher:
    def __init__(
        self,
        window_s: float,
        run_batch: Callable[[List[Entry]], None],
        co_possible: Callable[[], bool],
        wait_timeout_s: float = 120.0,
        swap_in_run: bool = False,
        gang_target: Optional[Callable[[], int]] = None,
    ):
        self.window_s = window_s
        self._run_batch = run_batch
        self._co_possible = co_possible
        self._wait_timeout_s = wait_timeout_s
        # gang formation (optional): the flusher's window wait ends EARLY
        # once `gang_target()` entries are pending — and, more importantly,
        # the window is allowed to be sized at a whole loop iteration
        # without costing that much per step. Without it, sessions whose
        # token loops happen to be phase-offset (e.g. staggered by their
        # prefills) form persistent co-batching COHORTS that a short fixed
        # window can never merge: each cohort's coalesced reply resyncs
        # only its own members. Waiting for the full gang once merges the
        # cohorts, and the merged gang then stays in lockstep, so the
        # steady-state wait collapses to the arrival jitter.
        self._gang_target = gang_target
        # swap_in_run=True: the flusher does NOT take the pending list at
        # wake-up; run_batch is called with an empty list and pulls the
        # batch itself via drain_pending() once it holds the device. This
        # is the CONTINUOUS-batching mode: entries that arrive while the
        # previous device step is still running keep accumulating until
        # the device actually frees, so batch size tracks device occupancy
        # instead of arrival phase (a wake-up swap fragments them into a
        # convoy of mini-batches queued on the device lock). The callback
        # owns every drained entry: result/error AND event delivery.
        self._swap_in_run = swap_in_run
        self._mu = lockwatch.make_lock("window")
        self._pending: List[Entry] = []
        self._flusher_active = False
        self.n_steps = 0  # flushed batches
        self.n_served = 0  # entries served across those batches
        # optional flight-recorder hook (the node wires its journal's
        # emit): a flusher that never completes within the wait timeout
        # is a wedged device step — the single worst windowing failure —
        # and must leave a typed `window.stall` event, not just a raised
        # TimeoutError that the client may swallow in a retry loop
        self.on_event: Optional[Callable[..., Any]] = None

    def _stall(self, where: str) -> None:
        from inferd_tpu.obs.events import emit_safely

        emit_safely(
            self.on_event, "window.stall", where=where,
            timeout_s=self._wait_timeout_s,
        )

    def submit(self, payload: Any) -> Any:
        entry = Entry(payload)
        with self._mu:
            self._pending.append(entry)
            i_flush = not self._flusher_active
            if i_flush:
                self._flusher_active = True
            wait = self._co_possible()

        if not i_flush:
            entry.event.wait(timeout=self._wait_timeout_s)
            if entry.error is not None:
                raise entry.error
            if not entry.event.is_set():
                self._stall("co_arrival")
                raise TimeoutError("batched decode flusher never completed")
            return entry.result

        if wait:
            if self._gang_target is None:
                time.sleep(self.window_s)
            else:
                # bounded gang wait: poll until every live idle session's
                # step is pending or the window cap elapses
                deadline = time.monotonic() + self.window_s
                while True:
                    if entry.event.is_set():
                        break  # our entry was invalidated mid-wait
                    want = self._gang_target()
                    with self._mu:
                        have = len(self._pending)
                    if want and have >= want:
                        break
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    time.sleep(min(0.0005, left))
        if self._swap_in_run:
            # release the flusher slot BEFORE running: a co-arrival during
            # our device step becomes the next flusher and queues on the
            # device lock, draining everything that accumulated meanwhile
            with self._mu:
                self._flusher_active = False
            try:
                self._run_batch([])
            except Exception as exc:
                # entries the callback never drained would hang their
                # submitters: fail whatever is still pending, plus our own
                # entry if the callback died before delivering it
                for e in self.drain_pending():
                    e.error = exc
                    e.event.set()
                if not entry.event.is_set():
                    entry.error = entry.error or exc
                    entry.event.set()
            entry.event.wait(timeout=self._wait_timeout_s)
            if entry.error is not None:
                raise entry.error
            if not entry.event.is_set():
                self._stall("swap_in_run")
                raise TimeoutError("batched decode flusher never completed")
            return entry.result
        with self._mu:
            batch, self._pending = self._pending, []
            self._flusher_active = False
        # entries invalidated between swap and here already have error set;
        # run the rest
        live = [e for e in batch if e.error is None]
        try:
            if live:
                self._run_batch(live)
                self.n_steps += 1
                self.n_served += len(live)
        except Exception as exc:
            for e in live:
                e.error = exc
                e.event.set()
            raise
        for e in live:
            e.event.set()
        if entry not in batch:
            # a concurrent flusher's drain_pending() absorbed this entry
            # into ITS device step before we could swap — wait for that
            # step to deliver, exactly like a non-flusher co-arrival
            entry.event.wait(timeout=self._wait_timeout_s)
            if not entry.event.is_set():
                self._stall("absorbed")
                raise TimeoutError("batched decode flusher never completed")
        if entry.error is not None:
            raise entry.error
        return entry.result

    def stats(self) -> dict:
        """Coalescing effectiveness counters (shared by both executors)."""
        return {
            "batched_steps": self.n_steps,
            "batched_tokens": self.n_served,
            "mean_batch": round(self.n_served / self.n_steps, 3)
            if self.n_steps
            else 0.0,
        }

    def drain_pending(self) -> List[Entry]:
        """Atomically take every live entry still waiting in the window.

        For CONTINUOUS batching: a flusher that has just acquired the
        device absorbs the entries that arrived while the previous step
        was still running (they would otherwise form a lagging
        under-filled window — arrival phase, not load, would set the
        batch size). The caller owns the drained entries end to end: it
        must set each one's result/error AND `event` when its step
        completes (the flush loop only signals entries of its own swap);
        a flusher whose own entry was drained waits on its event like any
        co-arrival."""
        with self._mu:
            batch, self._pending = self._pending, []
        live = [e for e in batch if e.error is None]
        if live:
            self.n_steps += 1
            self.n_served += len(live)
        return live

    def invalidate(self, pred: Callable[[Any], bool], error: Exception) -> None:
        """Fail-fast waiting entries whose payload matches `pred` (they have
        not started executing — entries already swapped into a running
        flush are the executor's responsibility via its in-flight
        accounting)."""
        with self._mu:
            still = []
            for e in self._pending:
                if pred(e.payload):
                    e.error = error
                    e.event.set()
                else:
                    still.append(e)
            self._pending[:] = still
