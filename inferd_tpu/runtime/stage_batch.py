"""Continuous batching for a PIPELINE STAGE: concurrent sessions' decode
steps through this stage run as ONE device step.

The swarm pipeline path — the paper's headline capability — served
concurrent sessions one at a time: Qwen3StageExecutor.process is hardwired
to batch=1, so every /forward ran the stage forward per session under the
device lock and aggregate tok/s DIVIDED by concurrency. This executor is
the stage-level sibling of runtime/batch_executor.BatchedExecutor (whole
model, one node) and core/batch.BatchedEngine (library layer): sessions
map to LANES of one shared [layers, lanes, max_len, ...] stage KV cache,
and single-token decode steps from whichever sessions co-arrive stack into
one jitted [lanes, 1, H] stage forward — weights are read once per batched
step instead of once per session per token (Orca-style iteration-level
batching, Yu et al. OSDI '22, applied per pipeline stage a la Petals'
server-side cross-client batching).

Division of labor with runtime/node.py: the NODE owns the arrival window
(runtime/window.WindowedBatcher) and the coalesced relay of co-batched
results; this executor owns lanes, admission, and the batched device step
(`process_batch`). `process()` keeps the single-session executor contract
(prefill chunks run per-lane; a solo decode step is a batch of one), so
warmup, chain mode, and non-windowed callers work unchanged.

Concurrency protocol (mirrors BatchedExecutor): `_mu` guards lane/session
bookkeeping, `_dev_lock` serializes device steps; a session is marked
in-flight for the duration of its step so LRU eviction/teardown can never
hand its lane to a new claimant while a stale write is pending (teardown
mid-step defers the lane free until the step drains — `_dying`).
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from inferd_tpu.config import ModelConfig
from inferd_tpu.core.cache import (
    RING_MARGIN, BlockPool, KVCache, PagedKVCache, sync_paged,
)
from inferd_tpu.core import prefix as prefixlib
from inferd_tpu.core.generate import bucket_len
from inferd_tpu.obs.events import emit_safely
from inferd_tpu.parallel.stages import StageSpec
from inferd_tpu.runtime.adapters import AdapterBindingMixin
from inferd_tpu.utils import lockwatch

Params = Any


class BatchedStageExecutor(AdapterBindingMixin):
    """Lane-slotted multi-session executor for one pipeline stage.

    Node executor contract (runtime/node.py): process(session_id, payload)
    -> {"hidden": [1, S, H]} or {"logits": [1, V]} (+ start_pos/real_len);
    end_session(session_id). Extra surface: process_batch(items) — the
    node's window flush callback — runs every item's decode step in ONE
    device dispatch and returns per-item results (exceptions per item,
    never batch-wide, so one bad session cannot fail its co-batch).
    """

    def __init__(
        self,
        cfg: ModelConfig,
        spec: StageSpec,
        stage_params: Params,
        lanes: int = 8,
        max_len: int = 4096,
        session_ttl_s: float = 600.0,
        block_size: int = 0,
        kv_blocks: int = 0,
        prefill_chunk: int = 0,
        adapters=None,
    ):
        import jax
        import jax.numpy as jnp

        self.cfg = cfg
        self.spec = spec
        self.params = stage_params
        self.lanes = lanes
        self.max_len = max_len
        self.ttl_s = session_ttl_s
        # multi-tenant LoRA registry (runtime/adapters.AdapterRegistry;
        # None = single-model serving): the registry holds THIS STAGE'S
        # layer slice of each adapter, sessions bind slots at admission,
        # and every co-batched dispatch gathers per-lane slot ids into
        # the unmerged apply (ops.lora.lane_delta) — a mixed-adapter
        # window is still ONE device step
        self.adapters = adapters
        self._session_adapter: Dict[str, str] = {}
        self._lane_slot = [0] * lanes  # slot 0 = the zero base adapter
        # server-side chunked prefill: a prompt longer than this many
        # tokens ingests as multiple dispatches, RELEASING the device lock
        # between chunks so co-batched decode windows interleave instead
        # of head-of-line-blocking behind a 4k-token admission (0 = off)
        self.prefill_chunk = int(prefill_chunk)

        # paged KV (block_size > 0): lanes map to chains of fixed-size
        # blocks through a block table instead of dense [lanes, max_len]
        # rows — allocation/eviction/sharing become per-block, and pinned/
        # cached shared prefixes map read-only into many lanes (CoW on
        # first divergent write). Dense (block_size == 0) stays the
        # bit-identical classic layout.
        self.pool: Optional[BlockPool] = None
        if block_size > 0:
            self.pool = BlockPool(
                cfg, spec.num_layers, lanes, max_len,
                block_size=block_size, num_blocks=kv_blocks or None,
            )
            self.cache = self.pool.cache
        else:
            self.cache = KVCache.create(
                cfg, spec.num_layers, lanes, max_len,
                layer_offset=spec.start_layer,
            )
        self.lengths = [0] * lanes  # host mirror (no device sync per step)
        self.free: List[int] = list(range(lanes))
        # tokens actually computed by prefill dispatches (the shared-prefix
        # saving is visible as the gap vs tokens admitted)
        self.prefill_tokens = 0

        # serializes device steps; INFERD_FAIR_DEVLOCK swaps in the
        # ticketed FIFO mutex (lockwatch.FairDeviceLock), and lockwatch
        # wraps either in an order-checking proxy when instrumented
        self._dev_lock = lockwatch.make_lock(
            "dev", fair=lockwatch.fair_devlock_enabled()
        )
        # guards session/lane bookkeeping
        self._mu = lockwatch.make_lock("mu")
        self._sessions: Dict[str, int] = {}  # session -> lane
        self._last_used: Dict[str, float] = {}
        self._inflight: Dict[str, int] = {}
        self._dying: Dict[int, str] = {}  # lane -> ended session mid-step
        # ring replay safety: per-lane high-water mark of positions ever
        # written by the CURRENT claimant (same contract as
        # BatchedExecutor._lane_hi)
        self._lane_hi: Dict[int, int] = {}
        # set by the node so a dropped session's entries still waiting in
        # the arrival window fail fast (runtime/window.invalidate) instead
        # of racing the lane's next owner
        self.on_drop: Optional[Callable[[str], None]] = None
        # flight-recorder hook (the node wires its journal's emit):
        # lane.evict events — an LRU eviction is a capacity decision that
        # silently costs some session its KV, exactly what a postmortem
        # needs on the record
        self.on_event: Optional[Callable[..., Any]] = None
        if self.pool is not None:
            # prefix-index eviction telemetry: journal the reclaimed
            # entry's age (time since last touch) so the memory plane can
            # tell LRU housekeeping (stale ages) from working-set thrash
            # (young ages). Reads self.on_event at CALL time — the node
            # wires the hook after construction.
            self.pool.on_evict = lambda key, age_s: emit_safely(
                self.on_event, "prefix.evict",
                age_ms=round(age_s * 1e3, 1),
                # digest_key: the ONE truncation — journal keys must stay
                # joinable against the gossiped `pfx` digest entries
                key=prefixlib.digest_key(key),
            )
        # co-batching effectiveness (stats()): device steps + entries served
        self._batched_steps = 0
        self._batched_tokens = 0

        cfg_ = cfg
        spec_ = spec
        from inferd_tpu.core.cache import lane_slice as _lane_slice
        from inferd_tpu.core.cache import lane_write as _lane_write
        from inferd_tpu.models import qwen3

        @partial(jax.jit, donate_argnames=("cache",))
        def _decode_all(params, x, cache: KVCache, lengths, ads=None):
            """One co-batched decode step over every lane.

            x: tokens [L, 1] on the first stage, hidden [L, 1, H]
            otherwise; lengths [L] = per-lane KV fill. Lanes without a
            live entry this window compute garbage at their own frontier
            slot; the slot is rewritten by the lane's next real step
            before its position can be read (the core/batch invariant).
            `ads`: the stage-sliced multi-tenant LoRA pools + per-lane
            slot ids — a mixed-adapter window stays ONE dispatch.
            """
            if spec_.is_first:
                hidden = qwen3.embed(params, x, cfg_)
            else:
                hidden = x
            positions = lengths[:, None]  # [L, 1] absolute per lane
            hidden, nc = qwen3.forward_layers_cached(
                params["layers"], cfg_, hidden, positions, cache, lengths,
                real_end=lengths + 1, layer_offset=spec_.start_layer,
                adapters=ads,
            )
            if spec_.is_last:
                logits = qwen3.unembed(params, cfg_, hidden)[:, 0]  # [L, V]
                return {"logits": logits}, nc
            return {"hidden": hidden}, nc

        @partial(jax.jit, donate_argnames=("cache",))
        def _prefill_lane(params, x, cache: KVCache, lane, start, n,
                          ads=None):
            """Chunk-ingest ONE lane: x [1, S_bucket] tokens or
            [1, S_bucket, H] hidden at absolute `start`; ragged prompts
            never pad against each other (per-lane prefill, the
            core/batch design)."""
            if spec_.is_first:
                hidden = qwen3.embed(params, x, cfg_)
            else:
                hidden = x
            s = hidden.shape[1]
            positions = start + jnp.broadcast_to(
                jnp.arange(s), hidden.shape[:2]
            )
            lc = _lane_slice(cache, lane)
            hidden, nc = qwen3.forward_layers_cached(
                params["layers"], cfg_, hidden, positions, lc, start,
                real_end=start + n, layer_offset=spec_.start_layer,
                adapters=ads,
            )
            cache = _lane_write(cache, lane, nc)
            if spec_.is_last:
                last = hidden[0, n - 1]
                logits = qwen3.unembed(params, cfg_, last[None, None, :])[0, 0]
                return {"logits": logits[None]}, cache  # [1, V]
            return {"hidden": hidden}, cache

        @partial(jax.jit, donate_argnames=("cache",))
        def _decode_all_paged(params, x, cache: PagedKVCache, lengths,
                              active, ads=None):
            """Paged sibling of _decode_all: writes scatter through the
            block table, reads gather through it, and NON-participating
            lanes' garbage writes are DROPPED (`active`) — blocks are
            shared property, so the dense path's overwrite-later
            invariant does not apply."""
            if spec_.is_first:
                hidden = qwen3.embed(params, x, cfg_)
            else:
                hidden = x
            positions = lengths[:, None]
            hidden, nc = qwen3.forward_layers_cached(
                params["layers"], cfg_, hidden, positions, cache, lengths,
                real_end=lengths + 1, layer_offset=spec_.start_layer,
                write_mask=active, adapters=ads,
            )
            if spec_.is_last:
                logits = qwen3.unembed(params, cfg_, hidden)[:, 0]
                return {"logits": logits}, nc
            return {"hidden": hidden}, nc

        @partial(jax.jit, donate_argnames=("cache",))
        def _prefill_lane_paged(params, x, cache: PagedKVCache, table_row,
                                start, n, ads=None):
            """Chunk-ingest ONE lane through its block-table row
            (table_row [1, MB]): the pools are global, so the scatter
            needs no lane_slice/lane_write round trip."""
            if spec_.is_first:
                hidden = qwen3.embed(params, x, cfg_)
            else:
                hidden = x
            s = hidden.shape[1]
            positions = start + jnp.broadcast_to(
                jnp.arange(s), hidden.shape[:2]
            )
            lc = PagedKVCache(
                k=cache.k, v=cache.v, table=table_row, length=cache.length
            )
            hidden, nc = qwen3.forward_layers_cached(
                params["layers"], cfg_, hidden, positions, lc, start,
                real_end=start + n, layer_offset=spec_.start_layer,
                adapters=ads,
            )
            cache = PagedKVCache(
                k=nc.k, v=nc.v, table=cache.table, length=cache.length
            )
            if spec_.is_last:
                last = hidden[0, n - 1]
                logits = qwen3.unembed(params, cfg_, last[None, None, :])[0, 0]
                return {"logits": logits[None]}, cache
            return {"hidden": hidden}, cache

        @partial(jax.jit, donate_argnames=("cache",))
        def _copy_blocks(cache: PagedKVCache, src, dst):
            """CoW block copies (src/dst [n] int32), in place under
            donation — applied before the next dispatch that reads a
            freshly split lane (core.cache.paged_copy_blocks)."""
            import dataclasses

            return dataclasses.replace(
                cache,
                k=cache.k.at[:, dst].set(cache.k[:, src]),
                v=cache.v.at[:, dst].set(cache.v[:, src]),
            )

        self._decode_all = _decode_all
        self._prefill_lane = _prefill_lane
        self._decode_all_paged = _decode_all_paged
        self._prefill_lane_paged = _prefill_lane_paged
        self._copy_blocks = _copy_blocks
        self._jax = jax
        self._jnp = jnp

        # multi-step fused decode over the co-batched lanes (single-stage
        # topologies only — a pipeline stage's next token depends on every
        # other stage, so multi-stage swarms keep the per-token relay and
        # amortize via co-batching alone). One compiled K-step scan
        # (models/qwen3.decode_k) decodes K on-device-sampled tokens for
        # every participating lane per dispatch.
        self._decode_k_all = None
        if spec.is_first and spec.is_last:
            # shared serving jit (models/qwen3.make_decode_k_serve) — the
            # same definition core.batch.BatchedEngine dispatches, so the
            # fuse_kstep_group contract cannot drift between executors
            self._decode_k_all = qwen3.make_decode_k_serve(cfg_)

    def co_possible(self) -> bool:
        """More than one live session -> a window wait can pay off.
        LOCK-FREE read (dict len is atomic): called under the node
        batcher's lock, while _drop_locked holds self._mu when it
        invalidates that same batcher — taking _mu here would be an
        ABBA deadlock."""
        return len(self._sessions) > 1

    def gang_target(self) -> int:
        """How many decode entries a window flusher should hope for: the
        live sessions that are NOT currently mid-step here (an in-flight
        session — e.g. one still prefilling — cannot also have a decode
        step waiting). LOCK-FREE reads, same reasoning as co_possible;
        the value is advisory (the window cap bounds any staleness)."""
        return len(self._sessions) - len(self._inflight)

    # -- lane/session bookkeeping (call under self._mu) ----------------------

    def _lane_for(self, session_id: str, new_ok: bool) -> int:
        lane = self._sessions.get(session_id)
        if lane is not None:
            self._last_used[session_id] = time.monotonic()
            return lane
        if not new_ok:
            raise ValueError(
                f"session {session_id}: unknown session resumed mid-stream "
                "(cache evicted or node restarted)"
            )
        if not self.free:
            from inferd_tpu.runtime.batch_executor import CapacityError

            victims = [
                s for s in self._sessions if not self._inflight.get(s)
            ]
            if not victims:
                raise CapacityError("all lanes busy with in-flight requests")
            oldest = min(victims, key=lambda s: self._last_used.get(s, 0.0))
            emit_safely(
                self.on_event, "lane.evict", session=oldest,
                lane=self._sessions.get(oldest),
                idle_s=round(
                    time.monotonic() - self._last_used.get(oldest, 0.0), 3
                ),
                claimant=session_id,
            )
            self._drop_locked(oldest)
        lane = self.free.pop()
        self._sessions[session_id] = lane
        self._last_used[session_id] = time.monotonic()
        self._lane_hi[lane] = 0
        return lane

    def _drop_locked(self, session_id: str) -> None:
        lane = self._sessions.pop(session_id, None)
        self._last_used.pop(session_id, None)
        self._release_adapter_locked(session_id)
        if lane is None:
            return
        # fail-fast entries still waiting in the node's arrival window: a
        # later flush must never write this lane on the old session's
        # behalf once a new claimant may own it
        if self.on_drop is not None:
            self.on_drop(session_id)
        if self._inflight.get(session_id):
            self._dying[lane] = session_id  # free deferred until drain
        else:
            self._free_lane_locked(lane)

    def _free_lane_locked(self, lane: int) -> None:
        self.lengths[lane] = 0
        self._lane_slot[lane] = 0  # back to the base adapter
        if self.pool is not None:
            # per-block free: cached/pinned prefix blocks survive through
            # their index references; everything else returns to the pool
            self.pool.release_lane(lane)
        self.free.append(lane)

    def _finish_locked(self, session_id: str, lane: int) -> None:
        self._inflight.pop(session_id, None)
        if self._dying.get(lane) == session_id:  # ended mid-step
            del self._dying[lane]
            self._free_lane_locked(lane)

    # -- admission (shared by decode co-batches and solo prefill) ------------

    def _admit_locked(
        self, session_id: str, start_pos: int, real_len: int, new_ok: bool,
        ensure_upto: Optional[int] = None,
    ) -> int:
        """Validate + in-flight-mark one chunk; returns its lane. MUST
        hold self._mu. ONE definition of the admission protocol
        (concurrency, restart reset, overflow, out-of-order, replay
        rollback under the ring margin) for both the co-batched decode
        path and the per-lane prefill path — mirrors
        BatchedExecutor.process admission.

        Paged extras: `ensure_upto` pre-allocates the lane's block chain
        to cover that many positions (decode/K-step dispatches write at
        known frontiers; prefill manages its own per-chunk ensure so
        shared-prefix mapping can claim the chain first), a restart
        releases the old chain per-block, and a replay rollback into a
        SHARED region queues copy-on-write splits for the device lock to
        apply — the rewrite must never scribble on blocks other lanes or
        the prefix index still read."""
        if self._inflight.get(session_id):
            raise ValueError(
                f"session {session_id}: concurrent request (one step at a "
                "time per session)"
            )
        lane = self._lane_for(session_id, new_ok=new_ok)
        owner = f"session {session_id}, lane {lane}"
        have = self.lengths[lane]
        if start_pos == 0 and have:
            # session restart under the same id: reset the lane
            self.lengths[lane] = 0
            self._lane_hi[lane] = 0
            if self.pool is not None:
                self.pool.release_lane(lane)
            have = 0
        if start_pos + real_len > self.max_len:
            raise BufferError(
                f"session {session_id}: KV overflow "
                f"({start_pos}+{real_len} > {self.max_len}, lane {lane})"
            )
        if start_pos != have:
            if not 0 < start_pos < have:
                raise ValueError(
                    f"session {session_id}: start_pos {start_pos} != cache "
                    f"length {have} (out-of-order chunk)"
                )
            hi = max(self._lane_hi.get(lane, 0), have)
            if self.cache.k_loc is not None and hi - start_pos > RING_MARGIN:
                raise ValueError(
                    f"session {session_id}: replay rollback to {start_pos} "
                    f"exceeds the ring margin (high-water mark {hi})"
                )
            # deterministic chunk REPLAY: roll the frontier back and
            # recompute (identical KV); preserve the pre-rollback frontier
            # as the ring high-water mark
            self._lane_hi[lane] = hi
            self.lengths[lane] = start_pos
            if self.pool is not None:
                before = self.pool.cow_splits
                self.pool.make_writable(lane, start_pos, owner=owner)
                if self.pool.cow_splits != before:
                    emit_safely(
                        self.on_event, "kv.cow_split", session=session_id,
                        lane=lane, from_pos=start_pos,
                        blocks=self.pool.cow_splits - before,
                    )
        if self.pool is not None and ensure_upto is not None:
            self.pool.ensure(lane, ensure_upto, owner=owner)
        self._inflight[session_id] = 1
        return lane

    # -- executor contract ---------------------------------------------------

    def process_batch(
        self,
        items: List[Tuple[str, Dict[str, Any]]],
        drain: Optional[Callable[[], List[Tuple[str, Dict[str, Any]]]]] = None,
    ) -> List[Any]:
        """ONE co-batched device step for every item's single-token decode.

        items: [(session_id, payload)] where each payload is a decode step
        ({"tokens": [1,1]} or {"hidden": [1,1,H]}, start_pos > 0,
        real_len == 1) — optionally carrying "decode_steps" (+ sampling/
        eos/key) for the multi-step fused path. Returns a list aligned
        with `items` (plus any drained extras, appended in drain order): a
        result dict per served item, or the Exception that rejected it
        (per-item — a stale session in the window must not fail its
        co-batch).

        Single-token items run as ONE batched step (client-side-sampling
        logits contract). Multi-step items (single-stage topologies only)
        fuse into ONE K-step scan per sampling config with K = the
        group's minimum budget-clamped request — co-batched lanes decode
        K steps per window when every lane has >= K budget, falling back
        toward K=1 at stop-condition/budget boundaries. Mixed windows run
        both dispatches under one device-lock hold.

        `drain` (optional) is called once the DEVICE LOCK is held and may
        return more items to fold into the same step — the continuous-
        batching hook: entries that arrived while the previous step was
        still running join this step instead of forming a lagging
        under-filled window (runtime/window.drain_pending).
        """
        from inferd_tpu.runtime.executor import (
            cache_intact, fuse_kstep_group, kstep_hi, parse_kstep,
        )

        out: List[Any] = [None] * len(items)
        served: List[Tuple[int, str, int, Any, int, Any]] = []
        taken: set = set()

        def admit(batch_items, base: int) -> None:
            """Validate + mark each item (under self._mu)."""
            for j, (sid, payload) in enumerate(batch_items):
                i = base + j
                try:
                    x, start_pos, real_len = self._parse(payload)
                    nm = payload.get("adapter")
                    if nm is not None and (
                        self.adapters is None
                        or self._session_adapter.get(sid) != str(nm)
                    ):
                        # decode steps are mid-session: the binding
                        # happened at admission — a mismatch is a routing
                        # bug, never served silently with other weights
                        raise ValueError(
                            f"session {sid}: decode-step adapter {nm!r} "
                            "does not match the admitted binding"
                        )
                    if real_len != 1 or start_pos <= 0:
                        raise ValueError(
                            "process_batch co-batches single-token decode "
                            f"steps only (real_len={real_len}, "
                            f"start_pos={start_pos})"
                        )
                    ks = parse_kstep(payload, self.max_len - start_pos)
                    if ks is not None and self._decode_k_all is None:
                        raise ValueError(
                            "decode_steps requires a single-stage "
                            "(whole-model) topology — pipeline stages "
                            "relay per token"
                        )
                    if sid in taken:
                        raise ValueError(
                            f"session {sid}: concurrent request (two steps "
                            "in one window)"
                        )
                    lane = self._admit_locked(
                        sid, start_pos, 1, new_ok=False,
                        # paged: the dispatch writes positions
                        # [start_pos, start_pos + K) — the chain must
                        # cover them before the jit scatters
                        ensure_upto=start_pos + (ks["k"] if ks else 1),
                    )
                    taken.add(sid)
                    served.append((i, sid, lane, x, start_pos, ks))
                except Exception as e:  # per-item rejection
                    out[i] = e

        with self._mu:
            admit(items, 0)
        if not served and drain is None:
            return out
        try:
            jnp = self._jnp
            with self._dev_lock:
                if drain is not None:
                    extra = drain()
                    if extra:
                        base = len(out)
                        out.extend([None] * len(extra))
                        with self._mu:
                            admit(extra, base)
                if not served:
                    return out
                # failure isolation is per DISPATCH (the batch_executor
                # contract): a mixed window runs one legacy step plus one
                # K-step scan per sampling group, and a raising dispatch
                # must fail only ITS entries — results another dispatch
                # already committed (lengths advanced, out[i] set) and
                # dispatches not yet run stay healthy. That holds for
                # HOST-side failures; a device-side failure after the jit
                # donated the cache invalidates the shared buffers, so
                # the window stops dispatching and fails the remaining
                # entries clearly (executor.cache_intact)
                poisoned = None
                legacy = [s for s in served if s[5] is None]
                kstep = [s for s in served if s[5] is not None]
                if legacy:
                    try:
                        with self._mu:
                            lens = list(self.lengths)
                            slot_ids = list(self._lane_slot)
                        ads = self._ads(slot_ids)
                        if self.spec.is_first:
                            xs = np.zeros((self.lanes, 1), np.int32)
                        else:
                            h0 = np.asarray(legacy[0][3])
                            xs = np.zeros(
                                (self.lanes, 1, h0.shape[-1]), h0.dtype
                            )
                        for _i, _sid, lane, x, _sp, _ks in legacy:
                            # x is already a HOST array (_parse
                            # materialized the wire payload); this is a
                            # host-to-host copy
                            xs[lane] = x[0]
                        xd = (jnp.asarray(xs) if self.spec.is_first
                              else jnp.asarray(xs, self.cfg.jnp_dtype))
                        if self.pool is not None:
                            act = np.zeros((self.lanes,), bool)
                            for _i, _sid, lane, _x, _sp, _ks in legacy:
                                act[lane] = True
                            res, self.cache = self._decode_all_paged(
                                self.params, xd, self._sync_paged(),
                                jnp.asarray(lens, jnp.int32),
                                jnp.asarray(act), ads=ads,
                            )
                        else:
                            res, self.cache = self._decode_all(
                                self.params, xd, self.cache,
                                jnp.asarray(lens, jnp.int32), ads=ads,
                            )
                        key = "logits" if self.spec.is_last else "hidden"
                        vals = np.asarray(res[key])
                        with self._mu:
                            for _i, _sid, lane, _x, _sp, _ks in legacy:
                                self.lengths[lane] += 1
                            self._batched_steps += 1
                            self._batched_tokens += len(legacy)
                        for i, _sid, lane, _x, sp, _ks in legacy:
                            out[i] = {
                                key: vals[lane][None],  # [1, 1, H] or [1, V]
                                "real_len": 1,
                                "start_pos": sp,
                            }
                    except Exception as e:
                        for i, _sid, _lane, _x, _sp, _ks in legacy:
                            out[i] = e
                        if not cache_intact(self.cache):
                            poisoned = e
                groups: Dict[tuple, list] = {}
                for s in kstep:
                    groups.setdefault(s[5]["sampling"], []).append(s)
                def run_group(grp):
                    with self._mu:
                        lens = list(self.lengths)
                        slot_ids = list(self._lane_slot)
                    kg, seq, n_new, nkeys, self.cache = fuse_kstep_group(
                        self._decode_k_all, self.params,
                        self._sync_paged() if self.pool is not None
                        else self.cache,
                        lens, self.lanes,
                        # x is already a HOST array (_parse materialized
                        # the wire payload)
                        [(lane, int(np.asarray(x)[0, 0]), ks)  # host-to-host copy, no device sync
                         for _i, _sid, lane, x, _sp, ks in grp],
                        ads=self._ads(slot_ids),
                    )
                    with self._mu:
                        n_served = 0
                        for _i, _sid, lane, _x, _sp, _ks in grp:
                            n = int(n_new[lane])  # n_new is a HOST array (materialized above)
                            old = self.lengths[lane]
                            self.lengths[lane] = old + n
                            self._lane_hi[lane] = max(
                                self._lane_hi.get(lane, 0),
                                kstep_hi(old, n, kg),
                            )
                            n_served += n
                        self._batched_steps += 1
                        # token-true co-batch accounting: K tokens per
                        # lane per dispatch, not 1 (the /stats and
                        # mean_batch numbers must reflect real tokens)
                        self._batched_tokens += n_served
                    for i, _sid, lane, _x, sp, _ks in grp:
                        n = int(n_new[lane])  # host array
                        out[i] = {
                            "tokens": [seq[:n, lane].tolist()],  # host array row unpack, no device sync
                            "real_len": n,
                            "decode_steps": kg,
                            "start_pos": sp,
                            "key": nkeys[lane].tolist(),  # host array row unpack, no device sync
                        }

                for _sampling, grp in groups.items():
                    if poisoned is not None:
                        err = RuntimeError(
                            "KV cache invalidated by an earlier dispatch "
                            f"failure in this window: {poisoned}"
                        )
                        for i, _sid, _lane, _x, _sp, _ks in grp:
                            out[i] = err
                        continue
                    try:
                        run_group(grp)
                    except Exception as e:
                        for i, _sid, _lane, _x, _sp, _ks in grp:
                            out[i] = e
                        if not cache_intact(self.cache):
                            poisoned = e
        except Exception as e:
            for i, _sid, _lane, _x, _sp, _ks in served:
                if out[i] is None:
                    out[i] = e
        finally:
            with self._mu:
                for _i, sid, lane, _x, _sp, _ks in served:
                    self._finish_locked(sid, lane)
        return out

    def _sync_paged(self):
        """core.cache.sync_paged over this executor's state: call under
        self._dev_lock; rebinds self.cache (the copy jit donates)."""
        self.cache = sync_paged(
            self.pool, self.cache, self._copy_blocks, self._mu
        )
        return self.cache

    def process(self, session_id: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Single-session contract: prefill chunks run per-lane; a decode
        step is a co-batch of one (the node's window is the place decode
        steps actually coalesce)."""
        x, start_pos, real_len = self._parse(payload)
        if real_len == 1 and start_pos > 0:
            res = self.process_batch([(session_id, payload)])[0]
            if isinstance(res, Exception):
                raise res
            return res
        return self._prefill_solo(session_id, payload, start_pos, real_len)

    def _parse(self, payload: Dict[str, Any]):
        """(x, start_pos, real_len) with x the raw [1, S(, H)] array."""
        start_pos = int(payload.get("start_pos", 0))
        if self.spec.is_first:
            x = np.asarray(payload["tokens"], dtype=np.int32)
        else:
            x = np.asarray(payload["hidden"])
        if x.ndim < 2 or x.shape[0] != 1:
            raise ValueError(f"stage batch expects [1, S(, H)], got {x.shape}")
        real_len = int(payload.get("real_len", x.shape[1]))
        return x, start_pos, real_len

    def _prefill_solo(
        self, session_id: str, payload: Dict[str, Any], start_pos: int,
        real_len: int,
    ) -> Dict[str, Any]:
        """Per-lane prompt ingestion, in up to three phases:

          1. shared-prefix SKIP (paged, whole-model stages, start_pos 0):
             full blocks whose chained token hash is already in the pool's
             prefix index map read-only into this lane — zero prefill
             FLOPs for the shared region, CoW on later divergence. At
             least the prompt's last token always computes (its logits
             are the response).
          2. chunked prefill: the remaining tokens ingest in
             `prefill_chunk`-token dispatches, RELEASING the device lock
             between chunks so co-batched decode windows interleave
             instead of stalling behind a long admission.
          3. registration (paged, first stage): the prompt's full blocks
             publish into the prefix index so later sessions sharing the
             prefix skip it.
        """
        jnp = self._jnp
        x, _, _ = self._parse(payload)
        acquired = self._resolve_adapter(session_id, payload, start_pos)
        try:
            with self._mu:
                lane = self._admit_locked(
                    session_id, start_pos, real_len, new_ok=start_pos == 0
                )
                self._bind_adapter_locked(
                    session_id, lane, start_pos, acquired
                )
        except Exception:
            # an admission that died before the binding consumed the
            # reference must give it back (slot refcount hygiene)
            if acquired is not None and acquired[1]:
                self.adapters.release(acquired[0])
            raise
        owner = f"session {session_id}, lane {lane}"
        try:
            pos = start_pos
            keys = None
            saved = 0
            with self._mu:
                ad_name = self._session_adapter.get(session_id)
                ads = self._ads([self._lane_slot[lane]])
            whole = self.spec.is_first and self.spec.is_last
            if self.pool is not None and self.spec.is_first and start_pos == 0:
                ids = [int(t) for t in x[0, :real_len]]
                # adapter sessions salt the chain: tenants must never
                # share prefix KV across adapters (core.prefix.block_keys)
                keys = prefixlib.block_keys(
                    ids, self.pool.block_size, salt=ad_name
                )
            if self.pool is not None and whole and start_pos == 0 and keys:
                # map at most the blocks covering real_len - 1 tokens: the
                # LAST prompt token must always compute (its logits seed
                # the first decode step)
                nmap = (real_len - 1) // self.pool.block_size
                with self._mu:
                    cov = self.pool.map_prefix(lane, keys[:nmap])
                if cov:
                    pos = saved = cov
                    with self._mu:
                        self.lengths[lane] = cov
                        self._lane_hi[lane] = max(
                            self._lane_hi.get(lane, 0), cov
                        )
                    emit_safely(
                        self.on_event, "prefix.hit", session=session_id,
                        lane=lane, tokens=cov,
                    )

            end = start_pos + real_len
            step = self.prefill_chunk if self.prefill_chunk > 0 else (
                end - pos
            )
            hidden_parts: List[Tuple[Any, int]] = []  # (device array, n)
            last = None
            key = "logits" if self.spec.is_last else "hidden"
            while pos < end:
                n = min(step, end - pos)
                chunk = x[:, pos - start_pos: pos - start_pos + n]
                # cap the padded bucket so the in-jit update can never
                # clamp into older slots near the end of the cache (the
                # BatchedExecutor._prefill_solo invariant); paged chains
                # are ensured per chunk instead
                b = min(bucket_len(n), self.max_len - pos)
                if self.spec.is_first:
                    padded = np.zeros((1, b), np.int32)
                    padded[0, :n] = chunk[0]
                    xd = jnp.asarray(padded)
                else:
                    padded = np.zeros((1, b, x.shape[2]), np.float32)
                    padded[0, :n] = chunk[0]
                    xd = jnp.asarray(padded, self.cfg.jnp_dtype)
                if self.pool is not None:
                    with self._mu:
                        self.pool.ensure(lane, pos + n, owner=owner)
                with self._dev_lock:
                    if self.pool is not None:
                        cache = self._sync_paged()
                        res, self.cache = self._prefill_lane_paged(
                            self.params, xd, cache,
                            jnp.asarray(self.pool.table[lane:lane + 1]),
                            jnp.int32(pos), jnp.int32(n), ads=ads,
                        )
                    else:
                        res, self.cache = self._prefill_lane(
                            self.params, xd, self.cache, jnp.int32(lane),
                            jnp.int32(pos), jnp.int32(n), ads=ads,
                        )
                    # keep results ON DEVICE inside the chunk loop — ONE
                    # boundary transfer after it (below)
                    if key == "hidden":
                        hidden_parts.append((res[key], n))
                    else:
                        last = res[key]
                    # advance BEFORE releasing the device lock: a window
                    # flush snapshots lengths under the same lock order
                    with self._mu:
                        self.lengths[lane] = pos + n
                        self._lane_hi[lane] = max(
                            self._lane_hi.get(lane, 0), pos + n
                        )
                        self.prefill_tokens += n
                pos += n
                if self.prefill_chunk > 0 and pos < end:
                    # explicit yield between chunks: threading.Lock is
                    # NOT fair — without this, the chunk loop can
                    # re-acquire the device before a waiting decode
                    # flusher ever wakes, and chunking would bound
                    # nothing. Sub-ms: noise next to a chunk dispatch.
                    # The ticketed FairDeviceLock grants in arrival
                    # order, so there the yield is dead weight.
                    if not lockwatch.is_fair(self._dev_lock):
                        time.sleep(0.0005)
            if self.pool is not None and whole and keys:
                with self._mu:
                    self.pool.register_prefix(lane, keys)
        finally:
            with self._mu:
                self._finish_locked(session_id, lane)
        if key == "hidden":
            # ship only the real rows (wire diet — the stage executor's
            # contract; downstream re-pads to its own bucket); one
            # device_get for every chunk's rows
            host = self._jax.device_get([p for p, _n in hidden_parts])
            trimmed = [h[:, :n_] for h, (_p, n_) in zip(host, hidden_parts)]
            val = (trimmed[0] if len(trimmed) == 1
                   else np.concatenate(trimmed, axis=1))
        else:
            val = np.asarray(last)
        return {
            key: val, "real_len": real_len, "start_pos": start_pos,
            # per-request shared-prefix saving: the node stamps it on the
            # prefill's compute span + kv.saved_tokens and strips it
            # before the reply/relay (key omitted on a cold prefill so
            # cold envelopes stay byte-identical to pre-digest builds)
            **({"tokens_saved": saved} if saved else {}),
        }

    def end_session(self, session_id: str) -> None:
        with self._mu:
            self._drop_locked(session_id)

    # -- prefix caching (paged mode) -----------------------------------------

    def pin_prefix(self, prefix_ids) -> int:
        """Prefill `prefix_ids` once into pool blocks and PIN them: the
        blocks stay resident (never evicted for space) and every later
        session whose prompt starts with them maps the region read-only
        instead of recomputing it — the Engine pin store generalized to
        refcounted pool blocks. Whole-model paged stages only. Returns
        the pinned token coverage (full blocks)."""
        if self.pool is None or not (self.spec.is_first and self.spec.is_last):
            raise ValueError(
                "pin_prefix needs paged KV on a whole-model stage"
            )
        ids = [int(t) for t in prefix_ids]
        if not ids:
            raise ValueError("prefix ids must be non-empty")
        keys = prefixlib.block_keys(ids, self.pool.block_size)
        sid = "__pin__" + keys[-1].hex() if keys else "__pin__short"
        # an ordinary prefill under a reserved session id registers the
        # blocks; the pin marks them and the teardown returns the lane
        # while the index references keep the blocks alive
        self.process(sid, {
            "tokens": [ids], "start_pos": 0, "real_len": len(ids),
        })
        with self._mu:
            self.pool.pin(keys)
        self.end_session(sid)
        return len(keys) * self.pool.block_size

    def unpin_prefix(self, prefix_ids) -> None:
        if self.pool is None:
            return
        with self._mu:
            self.pool.unpin(prefixlib.block_keys(
                [int(t) for t in prefix_ids], self.pool.block_size
            ))

    def fork_session(
        self, new_session_id: str, parent_session_id: str, prefix_len: int
    ) -> bool:
        """Seed a new session with the parent's first `prefix_len`
        positions. Paged mode maps the parent's full blocks READ-ONLY
        into the child (refcount, CoW on divergence) and copies only the
        partial tail block — the node's pinned-session fork flow rides
        the block pool instead of duplicating whole lane rows. Dense
        stage lanes return False (full prefill fallback), as before."""
        if self.pool is None or prefix_len <= 0:
            return False
        with self._mu:
            if self._session_adapter.get(parent_session_id):
                # the fork flow admits the child WITHOUT an adapter key:
                # decoding adapter-built KV with the base adapter would
                # diverge silently — the clean False re-prefills instead
                return False
            plane = self._sessions.get(parent_session_id)
            if (
                plane is None
                or self.lengths[plane] < prefix_len
                or new_session_id in self._sessions
            ):
                return False
            try:
                lane = self._lane_for(new_session_id, new_ok=True)
            except Exception:
                return False
            try:
                self.pool.fork_lane(
                    plane, lane, prefix_len,
                    owner=f"session {new_session_id}, lane {lane}",
                )
            except BufferError:
                self._drop_locked(new_session_id)
                return False
            self.lengths[lane] = prefix_len
            self._lane_hi[lane] = prefix_len
        return True

    # -- node surfaces (sweep loop, gossip adverts, /stats, kv gauge) --------

    @property
    def sessions(self):
        return self

    def sweep(self) -> int:
        if not self._mu.acquire(blocking=False):
            return 0
        try:
            now = time.monotonic()
            stale = [
                s for s, t in self._last_used.items()
                if now - t > self.ttl_s and not self._inflight.get(s)
            ]
            for s in stale:
                self._drop_locked(s)
            return len(stale)
        finally:
            self._mu.release()

    def ids(self):
        with self._mu:
            return list(self._sessions)

    def kv_occupancy(self) -> float:
        """Fraction of the KV budget in use — the serving memory-pressure
        signal obs.devtel gauges per scrape. Paged: blocks used / blocks
        total (the pool's true capacity unit); dense: filled positions /
        lanes x max_len."""
        with self._mu:
            if self.pool is not None:
                total = self.pool.num_blocks - 1
                return self.pool.blocks_used / float(total) if total else 0.0
            return sum(self.lengths) / float(self.lanes * self.max_len)

    def block_stats(self) -> Optional[Dict[str, Any]]:
        """Block-pool gauges for obs.devtel (None on the dense layout)."""
        if self.pool is None:
            return None
        with self._mu:
            return self.pool.block_stats()

    def prefix_digest(self) -> Optional[Dict[str, Any]]:
        """Gossip-ready digest of the pool's hot prefix index
        (core.prefix.make_digest over digest_keys: pinned entries first,
        then MRU) — the `pfx` record field entry routers score
        cache-affinity against. None on dense stages, inner pipeline
        stages (their index keys hash token ids they never see), and an
        empty index — the key is then OMITTED from gossip, never an
        empty decoy."""
        if self.pool is None or not (self.spec.is_first and self.spec.is_last):
            return None
        with self._mu:
            keys = self.pool.digest_keys(prefixlib.DIGEST_GOSSIP_KEYS)
            bs = self.pool.block_size
        if not keys:
            return None
        return prefixlib.make_digest(keys, bs)

    def kv_bytes(self) -> int:
        total = 0
        for arr in (self.cache.k, self.cache.v,
                    getattr(self.cache, "k_loc", None),
                    getattr(self.cache, "v_loc", None)):
            total += int(getattr(arr, "nbytes", 0) or 0)
        return total

    def __len__(self) -> int:
        with self._mu:
            return len(self._sessions)

    def __contains__(self, session_id: str) -> bool:
        with self._mu:
            return session_id in self._sessions

    def anatomy_target(self) -> Dict[str, Any]:
        """Live step-anatomy inputs for the continuous profiling plane
        (obs.prof.LiveAnatomy): the stage's REAL weight slice and paged/
        dense cache config. The cfg is re-shaped to the slice's layer
        count (profile_step scans params["layers"], which holds exactly
        this stage's layers) and the phase set is restricted to what the
        slice can express: embed only on the first stage, lm_head +
        sampling only on the last. ctx rounds UP to a 64-token bucket so
        the scan shapes (and their XLA compilations) stay stable as the
        decode frontier drifts."""
        import dataclasses as _dc

        phases = ["attention", "mlp", "kv_write"]
        if self.spec.is_first:
            phases.insert(0, "embed")
        if self.spec.is_last:
            phases.extend(["lm_head", "sampling"])
        with self._mu:
            ctx = max(self.lengths, default=0)
        ctx = -(-max(ctx, 32) // 64) * 64  # 64-token shape bucket
        return {
            "cfg": _dc.replace(self.cfg, num_layers=self.spec.num_layers),
            "params": self.params,
            "phases": tuple(phases),
            "ctx": min(ctx, max(self.max_len - 64, 32)),
            "batch": 1,
            "paged_block_size": (
                self.pool.block_size if self.pool is not None else 0
            ),
            # full-co-batch ceiling basis for roofline.live_frac: the
            # replica's aggregate tok/s is judged against what the chip
            # allows at ALL lanes, not one (obs.prof.AnatomyTarget)
            "ceiling_batch": self.lanes,
        }

    def stats(self) -> Dict[str, Any]:
        with self._mu:
            steps, toks = self._batched_steps, self._batched_tokens
            out = {
                "mode": "stage_batched",
                "stage": self.spec.stage,
                "lanes": self.lanes,
                "lanes_busy": self.lanes - len(self.free),
                "batched_steps": steps,
                "batched_tokens": toks,
                "mean_batch": round(toks / steps, 3) if steps else 0.0,
                "prefill_tokens": self.prefill_tokens,
            }
            if self.pool is not None:
                out["paged"] = self.pool.block_stats()
            if self.adapters is not None:
                out["adapters"] = self.adapters.stats()
            return out
