"""Multi-tenant LoRA serving: the adapter registry + adapter affinity.

One node serving thousands of tenants cannot merge thousands of adapters
(ops/lora merged mode is one adapter per replica, baked at load). The
registry holds a CATALOG of peft adapter directories (`run_node
--adapters DIR[,DIR...]`) and a bounded set of device-resident SLOTS:
stacked pools `[slots, L, in, r]` (A) / `[slots, L, r, out]` (B) per
targeted projection, slot 0 permanently the all-zero "base" adapter. A
session admitted with an `adapter` envelope key maps to a slot; the
batched stage forward gathers per-lane slot ids into the S-LoRA-style
unmerged apply (ops.lora.lane_delta — `y += scale[id]·(x@A[id])@B[id]`),
so a window mixing tenants runs as ONE dispatch.

Slot lifecycle mirrors the paged-KV BlockPool discipline (PR 8):
REFCOUNTED residency (a live session's adapter can never be evicted),
LRU eviction of idle unpinned slots when a cache-miss admission needs
one, pins for operator-designated hot tenants, `adapter.load` /
`adapter.evict` journal events and an `adapter.resident` gauge
(obs.devtel.adapter_series). Loads run on the ADMISSION path — disk read
+ host->device upload happen outside the executor's device lock, never
inside a decode window.

Routing: replicas gossip the resident catalog as a bounded `ada` field
(runtime/node.announce — the `pfx` digest pattern from PR 13), and
`AdapterAffinity` below plugs into the SAME duck-typed `affinity=` seam
both routers already score prefix digests through
(control.path_finder._rank_key / control.dstar.node_cost): an adapter
holder earns the bounded CACHE_AFFINITY_BONUS, suppressed under
admission-watermark/drain and dominated by the outlier penalty — a cold
healthy replica still beats a sick holder, and a miss is a HOT-LOAD on
the landing replica, never a reject.

jax is imported lazily inside methods: routers and the fleet simulator
import this module for the affinity scorer and must never initialize a
backend.
"""

from __future__ import annotations

import json
import os
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from inferd_tpu.config import ModelConfig
from inferd_tpu.obs.events import emit_safely
from inferd_tpu.utils import lockwatch

#: Resident-adapter names a replica GOSSIPS (the `ada` record field).
#: Names are short operator-chosen ids, so 32 of them stay well under the
#: `pfx` digest's wire budget; a fleet serving more residents than this
#: advertises its most-recently-used slice (stale affinity is only ever a
#: missed bonus — the landing replica hot-loads on a miss).
ADA_GOSSIP_MAX = 32


class AdapterCapacityError(RuntimeError):
    """Every slot is held by a live session or a pin — transient
    backpressure, the lane-pool CapacityError's adapter twin (the node
    maps it to a retryable 503)."""


class UnknownAdapterError(ValueError):
    """The payload names an adapter this node's catalog doesn't serve —
    a permanent config/routing error, never transient. The node maps it
    to a typed NON-retryable 409 (`unknown_adapter`): folding it into
    the generic `session_state` 409 would send the client into a
    deterministic full-restart retry loop that fails identically every
    attempt."""


class AdapterAffinity:
    """One session's adapter-affinity matcher against gossiped `ada`
    fields — duck-type compatible with core.prefix.AffinityProbe
    (`depth_frac(record) -> 0..1`), so both routers apply the SAME
    bounded bonus composition (suppressed on shedding/draining,
    dominated by the outlier penalty) without a second code path."""

    def __init__(self, name: str):
        self.name = str(name)

    def depth_frac(self, record: Dict[str, Any]) -> float:
        ada = record.get("ada")
        if not isinstance(ada, (list, tuple)):
            return 0.0
        return 1.0 if self.name in ada else 0.0


class _MaxAffinity:
    """Max-composition of several affinity scorers (prefix digest +
    adapter residency): bounded by construction — the combined bonus can
    never exceed one CACHE_AFFINITY_BONUS."""

    def __init__(self, parts):
        self.parts = parts

    def depth_frac(self, record: Dict[str, Any]) -> float:
        best = 0.0
        for p in self.parts:
            try:
                best = max(best, float(p.depth_frac(record)))
            except Exception:
                continue  # a malformed record must never break routing
        return best


def combine_affinity(*parts):
    """One affinity object over the non-None scorers (None when there
    are none) — what a router passes as `affinity=` when a session has
    both a prompt prefix probe and a tenant adapter."""
    live = [p for p in parts if p is not None]
    if not live:
        return None
    if len(live) == 1:
        return live[0]
    return _MaxAffinity(live)


def registry_can_serve(executor, name: Optional[str]) -> bool:
    """Whether `executor` could ever bind adapter `name` (None = base
    session: always). What the standby-replication receiver checks
    BEFORE accumulating a tenant shadow — a registry-less peer (or one
    whose catalog lacks the name) would decline at promotion anyway, so
    accepting its deltas silently voids the bounded-RPO promise."""
    if name is None:
        return True
    reg = getattr(executor, "adapters", None)
    return reg is not None and str(name) in reg.catalog


def parse_adapter_dirs(spec: str) -> Dict[str, str]:
    """`DIR[,DIR...]` -> {name: path} with name = the directory basename
    (the wire/envelope `adapter` key tenants address). Duplicate names
    are a config error, not a silent shadow."""
    out: Dict[str, str] = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name = os.path.basename(os.path.normpath(part))
        if not name:
            raise ValueError(f"--adapters entry {part!r} has no basename")
        if name in out:
            raise ValueError(
                f"--adapters names collide on {name!r} "
                f"({out[name]} vs {part}) — adapter names must be unique"
            )
        out[name] = part
    return out


class AdapterBindingMixin:
    """Session->slot plumbing shared by BOTH lane executors
    (runtime/batch_executor.BatchedExecutor and
    runtime/stage_batch.BatchedStageExecutor — they provide
    `self.adapters`, `self._session_adapter`, `self._lane_slot`, and
    `self._mu`; lock order is executor `_mu` -> registry `_mu`
    throughout). Hoisted here so the subtle refcount protocol
    (ref_taken handoff via the [name, ref_taken] cell, restart swap,
    rollback release) has ONE definition."""

    def _ads(self, ids):
        """Adapter-pool operand for ONE dispatch: the registry's stacked
        pools + these per-lane int32 slot ids (jit-visible like the
        paged block table), or None — no registry, or nothing loaded
        yet, and the jits trace the classic no-adapter graph."""
        if self.adapters is None:
            return None
        if not any(int(i) for i in ids):
            # every lane in this dispatch rides slot 0 (the base
            # adapter): route to the already-compiled no-adapter graph
            # instead of gathering pools for guaranteed-zero deltas
            return None
        pools = self.adapters.device_adapters()
        if pools is None:
            return None
        import jax.numpy as jnp

        return {**pools, "ids": jnp.asarray(ids, jnp.int32)}

    def _resolve_adapter(self, session_id: str, payload: Dict[str, Any],
                         start_pos: int):
        """Resolve the payload's `adapter` key BEFORE any executor lock:
        a cache-miss admission HOT-LOADS here (disk read + host->device
        upload through the registry's own lock — never under the device
        lock, never inside a decode window) instead of rejecting.
        Returns [name, ref_taken] or None (base adapter)."""
        name = payload.get("adapter")
        if name is None:
            return None
        name = str(name)
        if self.adapters is None:
            raise ValueError(
                f"session {session_id}: payload names adapter {name!r} "
                "but this replica serves no adapter registry (--adapters)"
                " — serving the base model instead would be silent "
                "tenant corruption"
            )
        if start_pos > 0:
            # mid-session chunks may re-state the adapter; a MISMATCH is
            # a routing bug surfaced loudly, never served silently
            with self._mu:
                have = self._session_adapter.get(session_id)
            if have != name:
                raise ValueError(
                    f"session {session_id}: mid-session adapter "
                    f"{name!r} != admitted {have!r}"
                )
            return [name, False]
        self.adapters.acquire(name)  # may hot-load (adapter.load event)
        return [name, True]

    def _bind_adapter_locked(self, session_id: str, lane: int,
                             start_pos: int, acquired) -> None:
        """Admission-time session->slot bookkeeping (under self._mu):
        a new admission (start_pos 0) consumes the pre-acquired
        reference; a restart under the same id swaps references. The
        lane's slot mirror is what decode windows gather ids from."""
        if start_pos != 0:
            return
        self._release_adapter_locked(session_id)
        if acquired is not None:
            self._session_adapter[session_id] = acquired[0]
            self._lane_slot[lane] = self.adapters.slot_of(acquired[0])
            acquired[1] = False  # reference consumed by the session
        else:
            self._lane_slot[lane] = 0

    def _release_adapter_locked(self, session_id: str) -> None:
        """Drop a session's binding + its registry reference (teardown
        and restart-swap paths; caller holds self._mu) — the slot
        becomes LRU-evictable with the last live session."""
        name = self._session_adapter.pop(session_id, None)
        if name is not None and self.adapters is not None:
            self.adapters.release(name)

    def session_adapters(self) -> Dict[str, str]:
        """{session_id: adapter name} snapshot (tenant sessions only) —
        the standby replicator's capability filter: a tenant session's
        shadow only goes to a peer gossiping the `ada` key, since any
        other peer could never promote it."""
        with self._mu:
            return dict(self._session_adapter)


class AdapterRegistry:
    """Device-resident stacked adapter pools with refcounted hot-load.

    `slots` counts TOTAL pool slots including the permanent zero base
    adapter at slot 0, so a registry with slots=5 serves at most 4
    distinct non-base adapters resident at once; the catalog may be far
    larger — cache-miss admissions hot-load over idle slots.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        dirs: Any,
        slots: int = 0,
        start_layer: int = 0,
        end_layer: Optional[int] = None,
        on_event=None,
        owner: str = "",
    ):
        if cfg.is_moe:
            raise ValueError(
                "the adapter registry targets dense decoder projections — "
                "MoE configs are unsupported (as in merge_adapter)"
            )
        if cfg.sliding_window > 0:
            raise ValueError(
                "the adapter registry does not support sliding-window "
                "models yet (ring-split KV stages bypass the batched "
                "apply) — serve --adapters on a uniform-layout model"
            )
        self.cfg = cfg
        self.owner = owner or "adapters"
        self.catalog: Dict[str, str] = (
            dict(dirs) if isinstance(dirs, dict) else parse_adapter_dirs(
                ",".join(dirs) if isinstance(dirs, (list, tuple)) else dirs
            )
        )
        if not self.catalog:
            raise ValueError("--adapters: no adapter directories given")
        self.start_layer = int(start_layer)
        self.end_layer = int(
            cfg.num_layers if end_layer is None else end_layer
        )
        self.num_layers = self.end_layer - self.start_layer
        if self.num_layers <= 0:
            raise ValueError(
                f"{self.owner}: adapter registry layer slice "
                f"[{self.start_layer}, {self.end_layer}) is empty"
            )
        # pool rank = the catalog's max rank: narrower adapters zero-pad
        # (zero rank rows contribute nothing to the delta, exactly).
        # Pools cover only the catalog's target UNION — an attention-only
        # catalog must not allocate the intermediate_size-wide MLP pools
        # or pay their zero-math gather+matmuls every dispatch
        # (apply_lane_delta passes through targets outside the pools)
        ranks, targets = zip(*(
            self._peek_meta(path) for path in self.catalog.values()
        ))
        self.rank = max(ranks)
        dims_all = {
            "q_proj": (cfg.hidden_size, cfg.q_dim),
            "k_proj": (cfg.hidden_size, cfg.kv_dim),
            "v_proj": (cfg.hidden_size, cfg.kv_dim),
            "o_proj": (cfg.q_dim, cfg.hidden_size),
            "gate_proj": (cfg.hidden_size, cfg.intermediate_size),
            "up_proj": (cfg.hidden_size, cfg.intermediate_size),
            "down_proj": (cfg.intermediate_size, cfg.hidden_size),
        }
        union = sorted(set().union(*targets) & set(dims_all))
        if not union:
            raise ValueError(
                f"{self.owner}: no adapter in the catalog targets a "
                f"supported decoder projection ({sorted(dims_all)})"
            )
        self.targets = tuple(union)
        self._dims = {name: dims_all[name] for name in self.targets}
        slots = int(slots or 0)
        if slots == 0:
            self.slots = len(self.catalog) + 1
        elif slots > 1:
            self.slots = slots
        else:
            # slot 0 is the permanent base adapter, so 1 slot can never
            # admit a tenant and negatives are nonsense — silently
            # substituting the default would be the opposite of what the
            # operator asked for (the check_exclusive_modes ethos)
            raise ValueError(
                f"{self.owner}: --adapter-slots {slots} is unservable — "
                "need >= 2 (slot 0 is the permanent base adapter) or 0 "
                "for catalog size + 1"
            )
        # flight-recorder hook (the node wires its journal's emit): loads
        # and evictions are capacity decisions the postmortem record needs
        self.on_event = on_event

        self._mu = lockwatch.make_lock("registry")
        self._slot_of: Dict[str, int] = {}  # resident name -> slot
        self._refs: Dict[str, int] = {}  # live-session references
        self._pins: set = set()
        # idle-since per resident (LRU eviction order); refreshed on
        # every release back to zero references
        self._idle_since: "OrderedDict[str, float]" = OrderedDict()
        self._free: List[int] = list(range(1, self.slots))
        self.loads = 0
        self.evictions = 0
        self._pools: Optional[Dict[str, Any]] = None  # built lazily

    # ------------------------------------------------------------- internals

    @staticmethod
    def _peek_meta(path: str):
        """(rank, targeted projections) from the adapter dir WITHOUT
        loading tensors: rank from adapter_config.json, targets from the
        safetensors key names (header-only read) — what __init__ sizes
        the pools from."""
        from safetensors import safe_open

        from inferd_tpu.ops.lora import _KEY_RE

        with open(os.path.join(path, "adapter_config.json")) as f:
            rank = int(json.load(f)["r"])
        targets = set()
        with safe_open(
            os.path.join(path, "adapter_model.safetensors"), framework="np"
        ) as f:
            for key in f.keys():
                m = _KEY_RE.search(key)
                if m is not None:
                    targets.add(m.group(2))
        return rank, targets

    def _ensure_pools_locked(self) -> Dict[str, Any]:
        """Zero-initialized stacked pools (+ scale) on first touch —
        [slots, L, in, r] / [slots, L, r, out] per catalog-targeted
        projection, all of slot 0 permanently zero (the base adapter)."""
        if self._pools is not None:
            return self._pools
        import jax.numpy as jnp

        s, L, r = self.slots, self.num_layers, self.rank
        dt = self.cfg.jnp_dtype
        self._pools = {
            "a": {
                name: jnp.zeros((s, L, din, r), dt)
                for name, (din, _dout) in self._dims.items()
            },
            "b": {
                name: jnp.zeros((s, L, r, dout), dt)
                for name, (_din, dout) in self._dims.items()
            },
            "scale": jnp.zeros((s,), jnp.float32),
        }
        return self._pools

    def _read_padded(self, name: str):
        """Disk-load `name` and build its zero-padded per-target f32 host
        rows — the EXPENSIVE half of a hot-load (safetensors read, pad,
        layer slice), run OUTSIDE self._mu so a cache-miss admission
        never stalls decode dispatches contending on device_adapters().
        Raises before any slot/eviction decision: an unreadable catalog
        entry must never evict an innocent resident."""
        import numpy as np

        from inferd_tpu.ops import lora as loralib

        path = self.catalog.get(name)
        if path is None:
            raise UnknownAdapterError(
                f"{self.owner}: unknown adapter {name!r} — this node's "
                f"catalog serves {sorted(self.catalog)}"
            )
        adapter = loralib.slice_adapter(
            loralib.load_adapter(self.cfg, path),
            self.start_layer, self.end_layer, owner=self.owner,
        )
        L, r = self.num_layers, self.rank
        rows = {}
        for target, (din, dout) in self._dims.items():
            a_new = np.zeros((L, din, r), np.float32)
            b_new = np.zeros((L, r, dout), np.float32)
            ab = adapter["layers"].get(target)
            if ab is not None:
                a, b = np.asarray(ab[0]), np.asarray(ab[1])
                a_new[:, :, : a.shape[-1]] = a
                b_new[:, : b.shape[1], :] = b
            rows[target] = (a_new, b_new)
        return rows, float(adapter["scale"])

    def _install_locked(self, name: str, rows, scale: float, t0: float) -> int:
        """Claim a slot (evicting an idle one if needed — only AFTER the
        disk read succeeded) and splice the prepared rows into the pools.
        MUST hold self._mu; the splice itself is a bounded set of device
        updates, the disk/pad work already happened in _read_padded."""
        if not self._free:
            victims = [
                n for n in self._idle_since
                if not self._refs.get(n) and n not in self._pins
            ]
            if not victims:
                raise AdapterCapacityError(
                    f"{self.owner}: all {self.slots - 1} adapter slots "
                    "hold live-session or pinned adapters"
                )
            victim = victims[0]  # oldest idle (OrderedDict insertion)
            vslot = self._slot_of.pop(victim)
            idle_s = time.monotonic() - self._idle_since.pop(victim)
            self._free.append(vslot)
            self.evictions += 1
            emit_safely(
                self.on_event, "adapter.evict", name=victim, slot=vslot,
                idle_s=round(idle_s, 3), claimant=name,
            )
            # the victim's pool rows are left in place and fully
            # overwritten by the claimant below (same-slot set covers
            # every layer/row — no stale residue can survive)
        pools = self._ensure_pools_locked()
        slot = self._free.pop(0)
        for target, (a_new, b_new) in rows.items():
            a_pool, b_pool = pools["a"][target], pools["b"][target]
            pools["a"][target] = a_pool.at[slot].set(
                a_new.astype(a_pool.dtype)
            )
            pools["b"][target] = b_pool.at[slot].set(
                b_new.astype(b_pool.dtype)
            )
        pools["scale"] = pools["scale"].at[slot].set(scale)
        self._slot_of[name] = slot
        self._idle_since[name] = time.monotonic()
        self.loads += 1
        emit_safely(
            self.on_event, "adapter.load", name=name, slot=slot,
            ms=round((time.perf_counter() - t0) * 1e3, 1),
        )
        return slot

    # --------------------------------------------------------------- surface

    def acquire(self, name: str) -> int:
        """Session admission: resolve `name` to a resident slot, hot-
        loading on a miss (disk + host->device OUTSIDE any executor
        device lock — the caller admits before it dispatches), and take
        a reference that shields the slot from eviction until
        release(). The disk read runs outside self._mu too, so a miss
        never stalls decode dispatches reading device_adapters();
        concurrent misses for one name race benignly — the loser
        discards its read and references the winner's slot."""
        with self._mu:
            slot = self._slot_of.get(name)
            if slot is not None:
                self._idle_since[name] = time.monotonic()
                self._idle_since.move_to_end(name)  # MRU refresh
                self._refs[name] = self._refs.get(name, 0) + 1
                return slot
        t0 = time.perf_counter()
        rows, scale = self._read_padded(name)
        with self._mu:
            slot = self._slot_of.get(name)
            if slot is None:
                slot = self._install_locked(name, rows, scale, t0)
            else:
                self._idle_since[name] = time.monotonic()
                self._idle_since.move_to_end(name)
            self._refs[name] = self._refs.get(name, 0) + 1
            return slot

    def release(self, name: str) -> None:
        with self._mu:
            n = self._refs.get(name, 0) - 1
            if n > 0:
                self._refs[name] = n
                return
            self._refs.pop(name, None)
            if name in self._slot_of:
                # back to idle: refresh the LRU stamp (evictable, newest
                # last — move_to_end keeps OrderedDict order = idle age)
                self._idle_since[name] = time.monotonic()
                self._idle_since.move_to_end(name)

    def slot_of(self, name: str) -> int:
        """Resident slot for a name a live session holds a reference on
        (the executor's per-lane id source). KeyError on non-resident —
        a session's slot is pinned by its reference, so this firing
        means the executor's bookkeeping broke, not the cache."""
        with self._mu:
            return self._slot_of[name]

    def pin(self, name: str) -> int:
        """Load (if needed) and pin `name` resident — never evicted
        until unpin, independent of session references."""
        with self._mu:
            slot = self._slot_of.get(name)
            if slot is not None:
                self._pins.add(name)
                return slot
        t0 = time.perf_counter()
        rows, scale = self._read_padded(name)
        with self._mu:
            slot = self._slot_of.get(name)
            if slot is None:
                slot = self._install_locked(name, rows, scale, t0)
            self._pins.add(name)
            return slot

    def unpin(self, name: str) -> None:
        with self._mu:
            self._pins.discard(name)

    def device_adapters(self) -> Optional[Dict[str, Any]]:
        """The stable pool pytree the batched jits take as an operand
        ({"a", "b", "scale"} — ops/lora pool contract; the executor adds
        its per-dispatch "ids"). None until the first load: an all-base
        window skips the delta entirely instead of paying zero-math."""
        with self._mu:
            if self._pools is None:
                return None
            return {
                "a": dict(self._pools["a"]),
                "b": dict(self._pools["b"]),
                "scale": self._pools["scale"],
            }

    def resident_names(self) -> List[str]:
        """Resident non-base adapters, LRU-oldest first, bounded at
        ADA_GOSSIP_MAX (most-recently-touched survive the cap) — the
        gossiped `ada` field."""
        with self._mu:
            names = [n for n in self._idle_since if n in self._slot_of]
        return names[-ADA_GOSSIP_MAX:]

    def resident_count(self) -> int:
        with self._mu:
            return len(self._slot_of)

    def stats(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "slots": self.slots,
                "resident": len(self._slot_of),
                "pinned": len(self._pins),
                "catalog": len(self.catalog),
                "rank": self.rank,
                "loads": self.loads,
                "evictions": self.evictions,
            }
