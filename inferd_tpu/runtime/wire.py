"""Tensor wire codec: dense envelopes with raw tensor buffers.

Replaces both reference wire formats — base64 JSON dicts (~33% size
overhead, /root/reference/petals/partitioned_models.py:11-26) and pickle
`torch.save` blobs (RCE-grade `torch.load` on untrusted bytes,
/root/reference/models/qwen3/server/server.py:16-18, SURVEY B8) — with a
safe dense encoding; nothing on the wire is ever executed or unpickled.

Two generations, one public pack/unpack surface:
  * inferd wire v1 (the default): a single-pass binary framing implemented
    natively in C++ (native/wirecodec.cpp) with a byte-identical pure-
    Python fallback (inferd_tpu.native.pyimpl) — tensors are memcpy'd
    straight between the source buffer and the frame;
  * legacy msgpack envelopes ({dtype, shape, raw bytes} tensor dicts),
    still decoded on receive for mixed-version swarms.
bfloat16 is carried via ml_dtypes' numpy dtype in both.
"""

from __future__ import annotations

import os
from typing import Any

import msgpack
import numpy as np

from inferd_tpu import native as _native
from inferd_tpu.native import pyimpl as _pyimpl

try:  # bfloat16 numpy support (ships with jax)
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BFLOAT16 = None

_TENSOR_KEY = "__nd__"

_ALLOWED_DTYPES = {
    "float32", "float16", "float64", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool",
}


def _encode_hook(obj: Any) -> Any:
    if hasattr(obj, "__array__") and not isinstance(obj, (bool, int, float, str, bytes)):
        a = np.asarray(obj)
        return {
            _TENSOR_KEY: 1,
            "dtype": a.dtype.name,
            "shape": list(a.shape),
            "data": a.tobytes(),
        }
    raise TypeError(f"unserializable type {type(obj)!r}")


def _decode_hook(obj: Any) -> Any:
    if isinstance(obj, dict) and obj.get(_TENSOR_KEY) == 1:
        name = obj["dtype"]
        if name not in _ALLOWED_DTYPES:
            raise ValueError(f"disallowed wire dtype {name!r}")
        dt = _BFLOAT16 if name == "bfloat16" else np.dtype(name)
        if dt is None:
            raise ValueError("bfloat16 on the wire but ml_dtypes unavailable")
        a = np.frombuffer(obj["data"], dtype=dt)
        shape = tuple(int(s) for s in obj["shape"])
        if a.size != int(np.prod(shape, dtype=np.int64)):
            raise ValueError(f"tensor payload size {a.size} != shape {shape}")
        return a.reshape(shape)
    return obj


# Rolling-upgrade escape hatch: nodes that still run the msgpack-only codec
# can't decode v1 frames, so during a mixed-version transition set
# INFERD_WIRE=legacy on the upgraded nodes until the fleet converges (v1
# nodes always DECODE legacy, so legacy is the safe common denominator).
# Read PER CALL, not at import: mixed-version tests (and the trace-key
# compatibility suite) toggle the knob without reimporting the module.
def _emit_legacy() -> bool:
    return os.environ.get("INFERD_WIRE", "v1").lower() == "legacy"


def pack(payload: Any) -> bytes:
    """Serialize a nested payload (dicts/lists/scalars/arrays) to bytes."""
    if _emit_legacy():
        return pack_legacy(payload)
    if _native.codec is not None:
        return _native.codec.pack(payload)
    return _pyimpl.pack(payload, _native.tensor_parts)


def unpack(data: bytes) -> Any:
    """Deserialize; tensors come back as numpy arrays. Never executes code."""
    if data[:3] == _pyimpl.MAGIC:
        if _native.codec is not None:
            return _native.codec.unpack(bytes(data))
        return _pyimpl.unpack(bytes(data), _native.tensor_build)
    # legacy msgpack envelope (mixed-version swarm)
    return msgpack.unpackb(
        data, object_hook=_decode_hook, raw=False, strict_map_key=False
    )


def pack_legacy(payload: Any) -> bytes:
    """msgpack envelope (kept for cross-version tests/tools)."""
    return msgpack.packb(payload, default=_encode_hook, use_bin_type=True)


# ---------------------------------------------------------------------------
# Multi-session /forward envelopes (coalesced relay)
# ---------------------------------------------------------------------------
#
# When a node co-batches decode steps of N sessions into one device step
# (runtime/stage_batch) and the entries share their next hop (the common
# case under affinity routing), the relay ships ONE envelope instead of N:
#
#   {"stage": s, "hidden": [N, 1, H],           # stacked decode activations
#    "multi": [frame, ...]}                     # one frame per session
#
# where each frame is the session's ordinary single-session envelope minus
# its hidden tensor ({"task_id", "session_id", "payload": {"start_pos",
# "real_len"}, optional "route"/"trace"}). The receiver fans frames back
# out into N single-session envelopes (split_forward) — downstream of the
# split every existing code path (rescue, re-route, chain mode) applies
# unchanged — and answers with a multi REPLY:
#
#   {"multi": [{"status": int, "body": bytes}, ...]}   # aligned with frames
#
# `body` is the already-wire-packed reply the session's own single relay
# would have received. Both wire generations carry these envelopes (plain
# dicts/lists/tensors/bytes — no new wire tags), and a node that never
# coalesces emits byte-identical single-session traffic, which is what
# keeps old nodes decodable in a mixed-version swarm (a coalescing node
# falls back to per-session relays when a peer rejects the multi form).

MULTI_KEY = "multi"

#: single-session envelope keys that must NOT ride a frame (they are
#: carried once at the top level or reconstructed by split_forward)
_FRAME_EXCLUDE = ("payload", "stage", MULTI_KEY)


def coalesce_forward(envs) -> dict:
    """ONE multi-session envelope from N single-session /forward envelopes
    whose payloads are single-token decode activations ({"hidden":
    [1, 1, H], "start_pos", "real_len"}) for the SAME stage."""
    if len(envs) < 2:
        raise ValueError("coalesce_forward needs >= 2 envelopes")
    stage = envs[0].get("stage")
    frames, rows = [], []
    for e in envs:
        if e.get("stage") != stage:
            raise ValueError("coalesce_forward: mixed stages")
        p = dict(e.get("payload") or {})
        h = np.asarray(p.pop("hidden"))
        if h.ndim != 3 or h.shape[0] != 1 or h.shape[1] != 1:
            raise ValueError(f"coalesce_forward: not a decode row {h.shape}")
        rows.append(h)
        frame = {k: v for k, v in e.items() if k not in _FRAME_EXCLUDE}
        frame["payload"] = p
        frames.append(frame)
    return {
        "stage": stage,
        MULTI_KEY: frames,
        "hidden": np.concatenate(rows, axis=0),
    }


def split_forward(env: dict):
    """Inverse of coalesce_forward: N single-session /forward envelopes
    from one multi envelope (validates the frame/row alignment)."""
    frames = env.get(MULTI_KEY)
    hidden = np.asarray(env["hidden"])
    if not isinstance(frames, list) or not frames:
        raise ValueError("multi envelope without frames")
    if hidden.ndim != 3 or hidden.shape[0] != len(frames):
        raise ValueError(
            f"multi envelope: {len(frames)} frames vs hidden {hidden.shape}"
        )
    out = []
    for i, frame in enumerate(frames):
        if not isinstance(frame, dict):
            raise ValueError("multi frame is not a dict")
        e = {k: v for k, v in frame.items() if k not in ("payload",)}
        e["stage"] = env.get("stage")
        p = dict(frame.get("payload") or {})
        p["hidden"] = hidden[i : i + 1]
        e["payload"] = p
        out.append(e)
    return out
