"""Tensor wire codec: dense envelopes with raw tensor buffers.

Replaces both reference wire formats — base64 JSON dicts (~33% size
overhead, /root/reference/petals/partitioned_models.py:11-26) and pickle
`torch.save` blobs (RCE-grade `torch.load` on untrusted bytes,
/root/reference/models/qwen3/server/server.py:16-18, SURVEY B8) — with a
safe dense encoding; nothing on the wire is ever executed or unpickled.

Two generations, one public pack/unpack surface:
  * inferd wire v1 (the default): a single-pass binary framing implemented
    natively in C++ (native/wirecodec.cpp) with a byte-identical pure-
    Python fallback (inferd_tpu.native.pyimpl) — tensors are memcpy'd
    straight between the source buffer and the frame;
  * legacy msgpack envelopes ({dtype, shape, raw bytes} tensor dicts),
    still decoded on receive for mixed-version swarms.
bfloat16 is carried via ml_dtypes' numpy dtype in both.
"""

from __future__ import annotations

import os
from typing import Any

import msgpack
import numpy as np

from inferd_tpu import native as _native
from inferd_tpu.native import pyimpl as _pyimpl

try:  # bfloat16 numpy support (ships with jax)
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BFLOAT16 = None

_TENSOR_KEY = "__nd__"

_ALLOWED_DTYPES = {
    "float32", "float16", "float64", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool",
}


def _encode_hook(obj: Any) -> Any:
    if hasattr(obj, "__array__") and not isinstance(obj, (bool, int, float, str, bytes)):
        a = np.asarray(obj)
        return {
            _TENSOR_KEY: 1,
            "dtype": a.dtype.name,
            "shape": list(a.shape),
            "data": a.tobytes(),
        }
    raise TypeError(f"unserializable type {type(obj)!r}")


def _decode_hook(obj: Any) -> Any:
    if isinstance(obj, dict) and obj.get(_TENSOR_KEY) == 1:
        name = obj["dtype"]
        if name not in _ALLOWED_DTYPES:
            raise ValueError(f"disallowed wire dtype {name!r}")
        dt = _BFLOAT16 if name == "bfloat16" else np.dtype(name)
        if dt is None:
            raise ValueError("bfloat16 on the wire but ml_dtypes unavailable")
        a = np.frombuffer(obj["data"], dtype=dt)
        shape = tuple(int(s) for s in obj["shape"])
        if a.size != int(np.prod(shape, dtype=np.int64)):
            raise ValueError(f"tensor payload size {a.size} != shape {shape}")
        return a.reshape(shape)
    return obj


# Rolling-upgrade escape hatch: nodes that still run the msgpack-only codec
# can't decode v1 frames, so during a mixed-version transition set
# INFERD_WIRE=legacy on the upgraded nodes until the fleet converges (v1
# nodes always DECODE legacy, so legacy is the safe common denominator).
# Read PER CALL, not at import: mixed-version tests (and the trace-key
# compatibility suite) toggle the knob without reimporting the module.
def _emit_legacy() -> bool:
    return os.environ.get("INFERD_WIRE", "v1").lower() == "legacy"


def pack(payload: Any) -> bytes:
    """Serialize a nested payload (dicts/lists/scalars/arrays) to bytes."""
    if _emit_legacy():
        return pack_legacy(payload)
    if _native.codec is not None:
        return _native.codec.pack(payload)
    return _pyimpl.pack(payload, _native.tensor_parts)


def unpack(data: bytes) -> Any:
    """Deserialize; tensors come back as numpy arrays. Never executes code."""
    if data[:3] == _pyimpl.MAGIC:
        if _native.codec is not None:
            return _native.codec.unpack(bytes(data))
        return _pyimpl.unpack(bytes(data), _native.tensor_build)
    # legacy msgpack envelope (mixed-version swarm)
    return msgpack.unpackb(
        data, object_hook=_decode_hook, raw=False, strict_map_key=False
    )


def pack_legacy(payload: Any) -> bytes:
    """msgpack envelope (kept for cross-version tests/tools)."""
    return msgpack.packb(payload, default=_encode_hook, use_bin_type=True)
