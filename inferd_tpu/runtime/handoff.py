"""Session-KV handoff payload codec — ONE schema for every executor.

The live-migration / graceful-shutdown handoff ships a session's KV
between replicas as {"k", "v", "length"[, "kv_dtype"][, "k_loc", "v_loc",
"hi"]}. Three executors (stage, batched, mesh) produce and consume it; a
single encode/validate pair here keeps the fp8 byte-view trick, the ring
fields, and the shape contract from drifting between them (each had begun
growing its own copy).

Buffers are batch-1: k/v are [L_global, 1, T, Nkv, D]; rings are
[L_sliding, 1, R, Nkv, D] and ship WHOLE (every slot may be live). Narrow
float dtypes the wire codec doesn't carry (fp8 KV) ride as same-shape
uint8 byte views plus their dtype name. `hi` is the ring high-water mark
(see the stage executor's replay-safety notes).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from inferd_tpu.config import ModelConfig
from inferd_tpu.core.cache import ring_slots, sliding_layer_ids


def encode(
    k: np.ndarray,
    v: np.ndarray,
    length: int,
    k_loc: Optional[np.ndarray] = None,
    v_loc: Optional[np.ndarray] = None,
    hi: Optional[int] = None,
) -> Dict[str, Any]:
    """Handoff payload from host arrays (k/v already sliced to the
    populated prefix; rings whole)."""
    payload: Dict[str, Any] = {"length": int(length)}
    if k.dtype.name.startswith("float8"):
        payload["kv_dtype"] = k.dtype.name  # itemsize 1: shape-preserving view
        k, v = k.view(np.uint8), v.view(np.uint8)
    payload["k"], payload["v"] = k, v
    if k_loc is not None:
        if k_loc.dtype.name.startswith("float8"):
            k_loc, v_loc = k_loc.view(np.uint8), v_loc.view(np.uint8)
        payload["k_loc"], payload["v_loc"] = k_loc, v_loc
        payload["hi"] = max(int(hi if hi is not None else length), int(length))
    return payload


def decode(
    payload: Dict[str, Any],
    cfg: ModelConfig,
    num_layers: int,
    layer_offset: int,
    max_len: int,
    want_ring: bool,
) -> Optional[Dict[str, Any]]:
    """Validate + decode a handoff payload against this executor's cache
    layout. Returns {"k", "v", "n", "k_loc", "v_loc", "hi"} (numpy, views
    restored to the shipped dtype) or None on ANY mismatch — adopting a
    malformed or wrong-layout payload must fail closed, not corrupt."""
    try:
        k = np.asarray(payload["k"])
        v = np.asarray(payload["v"])
        n = int(payload["length"])
    except Exception:
        return None
    if k.ndim != 5 or v.shape != k.shape:
        return None
    kd = payload.get("kv_dtype")
    if kd is not None:  # fp8 shipped as uint8 byte views — view BOTH back
        if (
            k.dtype != np.uint8
            or v.dtype != np.uint8
            or not str(kd).startswith("float8")
        ):
            return None
        try:
            import jax.numpy as jnp

            dt = jnp.dtype(str(kd))
        except Exception:
            return None
        k, v = k.view(dt), v.view(dt)
    n_loc = (
        len(sliding_layer_ids(cfg, num_layers, layer_offset)) if want_ring else 0
    )
    if (n_loc > 0) != ("k_loc" in payload):
        return None  # layout mismatch (e.g. peer ran uniform buffers)
    expect = (num_layers - n_loc, 1, cfg.num_kv_heads, cfg.head_dim)
    got = (k.shape[0], k.shape[1], k.shape[3], k.shape[4])
    if got != expect or k.shape[2] < n or n <= 0 or n > max_len:
        return None
    k_loc = v_loc = None
    if n_loc:
        k_loc = np.asarray(payload["k_loc"])
        v_loc = np.asarray(payload["v_loc"])
        if kd is not None:
            if k_loc.dtype != np.uint8 or v_loc.dtype != np.uint8:
                return None
            k_loc, v_loc = k_loc.view(k.dtype), v_loc.view(k.dtype)
        expect_loc = (
            n_loc, 1, ring_slots(cfg), cfg.num_kv_heads, cfg.head_dim
        )
        if k_loc.shape != expect_loc or v_loc.shape != k_loc.shape:
            return None
    return {
        "k": k, "v": v, "n": n, "k_loc": k_loc, "v_loc": v_loc,
        "hi": max(int(payload.get("hi", n)), n),
    }
