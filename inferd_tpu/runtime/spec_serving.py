"""Shared speculative-serving driver for slot/lane executors.

The continuous-batching executor (lanes, runtime/batch_executor) and the
in-mesh pipelined executor (microbatch slots, runtime/mesh_executor) drive
speculation identically at the session level: per-sampling-config runners
in a small LRU, a window batcher coalescing concurrent sessions' rounds,
an open-to-close in-flight hold protecting idle slots from eviction, and a
deferred free when a close races a round still on the device. That logic
is concurrency-subtle and must not fork — it lives HERE once; each
executor supplies only the storage-specific hooks (claim/prefill/flush).

Hook surface a subclass must provide (see BatchedExecutor/MeshExecutor):
  _spec_mu                      lock guarding session bookkeeping (also
                                used for _inflight/_dying)
  _spec_session_slot(sid)       -> Optional[int] lane/slot of a session
  _spec_session_len(sid, slot)  -> int current target KV length
  _spec_free_slot(sid, slot)    free the lane/slot + mirrors (under _spec_mu)
  _spec_drop(sid)               session teardown on close (under _spec_mu):
                                unmap + invalidate pending decode entries,
                                deferring the free via _dying if in-flight
  _spec_new_runner(sampling)    -> runner (LaneSpecRunner / MeshSpecRunner)
  _spec_plain_submit(slot, tok, sid) -> logits row [V] via the REGULAR
                                decode batcher (the tail path)
  _run_spec_batch(runner, entries)  the device flush (sets e.result)
  spec_open(sid, ids, sampling, seed)  per-executor (claim + prefill)

Shared state lives in self._spec (dict), created by _spec_init().
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Optional

import numpy as np


class SpecForkMiss(Exception):
    """A pinned-prefix fork declined (unknown/short/evicted parent): the
    caller falls back to a plain open or the regular loop. A DEDICATED
    type so the fallback catch can't swallow KeyError/IndexError from
    genuine bookkeeping bugs (those must keep logging)."""


class SpecServing:
    _spec: Optional[dict] = None
    _spec_window_s: float = 0.003

    # -- shared state --------------------------------------------------------

    def _spec_init(self, k: int, slots: int) -> dict:
        """The shared bookkeeping dict (executors add their own keys)."""
        return {
            "k": k,
            "dlens": [0] * slots,  # per-slot draft cache lengths
            "runners": OrderedDict(),  # runner key -> (runner, batcher)
            "sid": {},  # session -> (runner, batcher, runner_key)
            "keys": {},  # session -> PRNG chain (sampled configs)
            "count": {},  # runner key -> live spec session count
            "build_ms": 0.0,  # slowest runner build wall time seen
            # cumulative round counters folded in from EVICTED runners'
            # batchers (stats must be monotonic across evictions)
            "rounds_retired": 0,
            "round_sessions_retired": 0,
        }

    @property
    def cap(self) -> int:
        """Effective per-session KV capacity: max_len minus the
        speculative verify-chunk headroom when speculation is enabled
        (EVERY live session must stay k+1 short of the physical buffer —
        core.spec_batch headroom contract)."""
        if self._spec is None:
            return self.max_len
        return self.max_len - (self._spec["k"] + 1)

    def spec_enabled(self) -> bool:
        return self._spec is not None

    @property
    def spec_k(self) -> int:
        return self._spec["k"] if self._spec else 0

    # -- per-sampling-config runner LRU --------------------------------------

    def _spec_runner(self, sampling):
        """Build-or-get (runner, batcher, key) for a sampling config.
        Runner construction only defines closures (compile happens on the
        first round); a small true-LRU bounds adversarial config cycling,
        and live sessions hold their own refs so eviction never breaks
        them."""
        from inferd_tpu.core.spec_batch import spec_key
        from inferd_tpu.runtime.window import WindowedBatcher

        sp = self._spec
        key, norm = spec_key(sampling)
        with self._spec_mu:
            ent = sp["runners"].get(key)
            if ent is None:
                t0 = time.monotonic()
                runner = self._spec_new_runner(norm)
                batcher = WindowedBatcher(
                    self._spec_window_s,
                    lambda entries, _r=runner: self._run_spec_batch(_r, entries),
                    co_possible=lambda _k=key: sp["count"].get(_k, 0) > 1,
                )
                sp["build_ms"] = max(
                    sp["build_ms"], (time.monotonic() - t0) * 1e3
                )
                ent = (runner, batcher)
                sp["runners"][key] = ent
                while len(sp["runners"]) > 4:
                    old_key, (_, old_b) = sp["runners"].popitem(last=False)
                    s = old_b.stats()
                    sp["rounds_retired"] += s["batched_steps"]
                    sp["round_sessions_retired"] += s["batched_tokens"]
                    if not sp["count"].get(old_key):
                        sp["count"].pop(old_key, None)
            else:
                sp["runners"].move_to_end(key)
            return ent[0], ent[1], key

    @staticmethod
    def _spec_entry_result(want, toks_row, n, lps_row=None, tis_row=None,
                           tls_row=None):
        """ONE definition of the per-entry flush result the node unpacks
        positionally — (toks, n) or (toks, n, lps, tops) — so the two
        executors' flushes can never desync the wire shape."""
        if want:
            return (
                toks_row[:n].tolist(), n, lps_row[:n].tolist(),
                [(tis_row[j].tolist(), tls_row[j].tolist())
                 for j in range(n)],
            )
        return (toks_row[:n].tolist(), n)

    # -- in-flight round accounting ------------------------------------------

    def _spec_round_enter(self, session_id: str) -> None:
        """Bump the session's in-flight count for one device round (MUST
        hold _spec_mu). The count is 1 (the open-to-close hold) + rounds
        currently submitted — an external close mid-round then defers the
        free via _dying exactly like process() does."""
        self._inflight[session_id] = self._inflight.get(session_id, 0) + 1

    def _spec_round_exit(self, session_id: str, slot: int) -> None:
        """Drop one round's count; complete a deferred free if the session
        was closed while this round was on the device."""
        with self._spec_mu:
            left = self._inflight.get(session_id, 1) - 1
            if left <= 0:
                self._inflight.pop(session_id, None)
                if self._dying.get(slot) == session_id:
                    del self._dying[slot]
                    self._spec_free_slot(session_id, slot)
            else:
                self._inflight[session_id] = left

    # -- session drive --------------------------------------------------------

    def spec_step(self, session_id: str, last_tok: int, prev_tok: int):
        """One speculative round (coalesces with other sessions' rounds in
        the same window). Returns (tokens, n_new) — or (tokens, n_new,
        lps, tops) when the session opened with want_lp — or None when the
        session is within the verify chunk of the spec cap (caller
        switches to spec_tail_step)."""
        import jax

        sp = self._spec
        with self._spec_mu:
            slot = self._spec_session_slot(session_id)
            if slot is None or session_id not in sp["sid"]:
                raise ValueError(f"unknown spec session {session_id}")
            runner, batcher = sp["sid"][session_id][:2]
            if self._spec_session_len(session_id, slot) + runner.k + 1 > self.cap:
                return None
            sub = None
            if runner.sampling.temperature > 0.0:
                key, sub_j = jax.random.split(sp["keys"][session_id])
                sp["keys"][session_id] = key
                sub = np.asarray(sub_j)
            self._spec_round_enter(session_id)
        try:
            return batcher.submit(
                (slot, session_id, last_tok, prev_tok, sub)
            )
        finally:
            self._spec_round_exit(session_id, slot)

    def spec_tail_step(self, session_id: str, last_tok: int):
        """Plain one-token step for the tail of a spec generation (inside
        the verify-chunk headroom): rides the REGULAR decode batch, then
        samples with the session's own chain — still exactly target-only
        sampling. Returns (token, lp_entry) — lp_entry is (lp, top_ids,
        top_lps) for want_lp sessions, else None."""
        import jax

        sp = self._spec
        with self._spec_mu:
            slot = self._spec_session_slot(session_id)
            if slot is None or session_id not in sp["sid"]:
                raise ValueError(f"unknown spec session {session_id}")
            runner, _, _, want_lp = sp["sid"][session_id]
            if self._spec_session_len(session_id, slot) + 1 > self.cap:
                raise BufferError(
                    f"session {session_id}: KV overflow at spec cap {self.cap}"
                )
            sub = None
            if runner.sampling.temperature > 0.0:
                key, sub_j = jax.random.split(sp["keys"][session_id])
                sp["keys"][session_id] = key
                sub = sub_j
            self._spec_round_enter(session_id)
        try:
            row = self._spec_plain_submit(slot, int(last_tok), session_id)
        finally:
            self._spec_round_exit(session_id, slot)
        if sub is None:
            tok = int(np.argmax(row))
            return tok, (runner.row_lp(row, tok) if want_lp else None)
        return runner.first_token(row, sub), None

    def spec_warmup(self) -> None:
        """Compile the greedy spec path (prefill + round) off the serving
        critical path: one tiny open/round/close per want_lp variant
        (runtime/node.py prebuild task — want_lp is a STATIC jit arg, so
        the logprob flavor is its own executable; without warming it the
        first logprob request would pay the round compile under the
        device lock, stalling every coalesced session)."""
        from inferd_tpu.config import SamplingConfig

        for want_lp in (False, True):
            sid = f"spec-warmup-{int(want_lp)}"
            first, _ = self.spec_open(
                sid, [1, 2], SamplingConfig(temperature=0.0),
                want_lp=want_lp,
            )
            try:
                self.spec_step(sid, first, 0)
            finally:
                self.spec_close(sid)

    def spec_close(self, session_id: str) -> None:
        """End a speculative session: release the open-to-close hold and
        tear the session down. A round still ON THE DEVICE (e.g. the
        handler task was cancelled mid-await) keeps its own in-flight
        count, so the teardown defers the slot free via _dying until
        _spec_round_exit drains it — a new claimant can never share the
        slot with a stale round's write."""
        sp = self._spec
        with self._spec_mu:
            if sp is not None:
                ent = sp["sid"].pop(session_id, None)
                sp["keys"].pop(session_id, None)
                if ent is not None:
                    batcher, rkey = ent[1], ent[2]
                    left = max(0, sp["count"].get(rkey, 0) - 1)
                    if left or rkey in sp["runners"]:
                        sp["count"][rkey] = left
                    else:
                        sp["count"].pop(rkey, None)
                    slot = self._spec_session_slot(session_id)
                    if slot is not None:
                        batcher.invalidate(
                            lambda payload, _s=slot: payload[0] == _s,
                            ValueError(f"session {session_id} closed"),
                        )
            # release only the HOLD: rounds mid-device keep their count
            left = self._inflight.get(session_id, 1) - 1
            if left <= 0:
                self._inflight.pop(session_id, None)
            else:
                self._inflight[session_id] = left
            self._spec_drop(session_id)

    def spec_stats(self) -> dict:
        sp = self._spec
        if sp is None:
            return {}
        with self._spec_mu:
            out = {
                "spec_sessions": len(sp["sid"]),
                "spec_runners": len(sp["runners"]),
            }
            if sp["build_ms"]:
                out["spec_engine_build_ms"] = round(sp["build_ms"], 3)
            steps = sp["rounds_retired"]
            served = sp["round_sessions_retired"]
            for _, batcher in sp["runners"].values():
                s = batcher.stats()
                steps += s["batched_steps"]
                served += s["batched_tokens"]
            out["spec_rounds"] = steps
            out["spec_round_sessions"] = served
            return out
