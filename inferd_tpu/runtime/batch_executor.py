"""Continuous-batching stage executor: concurrent sessions' decode steps
coalesce into ONE device step.

The reference serves strictly one request at a time per node (a lone
pipeline pass per token, /root/reference/petals/send_message.py:27-49 /
server.py:25-54); every session re-reads all the weights per token. This
executor keeps the node's `/forward` + client-side-sampling contract but
maps sessions to lanes of core.batch.BatchedEngine and batches the
single-token decode steps of whichever sessions arrive within a short
window — aggregate tok/s then scales with concurrency instead of dividing
by it (weights are read once per BATCHED step).

Concurrency design (process() runs on the node's worker thread pool):
  * decode steps (real_len == 1 at the session's frontier) enqueue into a
    pending batch; the FIRST arrival becomes the flusher — it waits up to
    `window_ms` for co-arrivals, takes the device lock, runs one batched
    step for every pending lane, and distributes each lane's logits to its
    waiting thread;
  * prefill chunks (multi-token or unknown session) run solo under the
    same device lock (per-lane cache writes, other lanes untouched);
  * whole-model executor: is_first and is_last (tokens in, last-token
    logits out) — like MeshExecutor it hosts a 1-stage swarm topology.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from inferd_tpu.config import ModelConfig
from inferd_tpu.core.batch import BatchedEngine
from inferd_tpu.core.cache import RING_MARGIN, sync_paged
from inferd_tpu.core import prefix as prefixlib
from inferd_tpu.core.generate import bucket_len
from inferd_tpu.obs.events import emit_safely
from inferd_tpu.runtime.adapters import AdapterBindingMixin
from inferd_tpu.runtime.spec_serving import SpecForkMiss, SpecServing
from inferd_tpu.runtime.window import WindowedBatcher
from inferd_tpu.utils import lockwatch

Params = Any


class CapacityError(RuntimeError):
    """All lanes are serving in-flight requests — transient backpressure
    (the node maps this to a retryable 503, unlike deterministic KV
    overflow which is a 409)."""


class BatchedExecutor(SpecServing, AdapterBindingMixin):
    """Whole-model, lane-per-session executor with windowed decode batching.

    Node executor contract (runtime/node.py): process(session_id, payload)
    -> {"logits": [1, V], ...}; end_session(session_id).
    """

    is_first = True
    is_last = True

    def __init__(
        self,
        cfg: ModelConfig,
        params: Params,
        lanes: int = 8,
        max_len: int = 4096,
        window_ms: float = 3.0,
        session_ttl_s: float = 600.0,
        block_size: int = 0,
        kv_blocks: int = 0,
        prefill_chunk: int = 0,
        adapters=None,
    ):
        self.cfg = cfg
        self.engine = BatchedEngine(
            cfg, params, lanes=lanes, max_len=max_len,
            block_size=block_size, kv_blocks=kv_blocks,
        )
        # multi-tenant LoRA registry (runtime/adapters.AdapterRegistry;
        # None = single-model serving, every jit traces exactly as
        # before): sessions admitted with an `adapter` payload key map to
        # registry slots, and every batched dispatch gathers per-lane
        # slot ids into the unmerged apply (ops.lora.lane_delta)
        self.adapters = adapters
        self._session_adapter: Dict[str, str] = {}
        self._lane_slot = [0] * lanes  # slot 0 = the zero base adapter
        # paged KV (block_size > 0, core.cache.BlockPool): per-block
        # allocation/eviction + refcounted shared-prefix blocks with CoW;
        # None = the classic dense lane slab
        self.pool = self.engine.pool
        # server-side chunked prefill: dispatches of at most this many
        # tokens with the device lock RELEASED between them, so decode
        # windows interleave instead of stalling behind a long admission
        self.prefill_chunk = int(prefill_chunk)
        self.prefill_tokens = 0  # tokens actually computed by prefill
        self.max_len = max_len
        self.ttl_s = session_ttl_s

        # serializes device steps; INFERD_FAIR_DEVLOCK swaps in the
        # ticketed FIFO mutex (lockwatch.FairDeviceLock), and lockwatch
        # wraps either in an order-checking proxy when instrumented
        self._dev_lock = lockwatch.make_lock(
            "dev", fair=lockwatch.fair_devlock_enabled()
        )
        # ring replay safety: per-lane high-water mark of positions ever
        # written THIS claimant; only diverges from the lane length across
        # replay rollbacks (effective hi = max(mark, length))
        self._lane_hi: Dict[int, int] = {}
        # guards session/lane + pending state
        self._mu = lockwatch.make_lock("mu")
        self._sessions: Dict[str, int] = {}  # session -> lane
        self._last_used: Dict[str, float] = {}
        self._inflight: Dict[str, int] = {}  # session -> active request count
        self._dying: Dict[int, str] = {}  # lane -> ended session awaiting drain
        self._batcher = WindowedBatcher(
            window_ms / 1e3,
            self._run_decode_batch,
            # a solo session should not pay the window latency
            co_possible=lambda: len(self._sessions) > 1,
        )
        self._spec_window_s = window_ms / 1e3
        # lane-batched speculation (enable_spec): None until enabled
        self._spec: "dict | None" = None
        # flight-recorder hook (the node wires its journal's emit):
        # lane.evict events for the fleet postmortem record
        self.on_event = None
        if self.pool is not None:
            # prefix-index eviction telemetry (same contract as the stage
            # executor): journal the reclaimed entry's age so the memory
            # plane can tell housekeeping from working-set thrash
            self.pool.on_evict = lambda key, age_s: emit_safely(
                self.on_event, "prefix.evict",
                age_ms=round(age_s * 1e3, 1),
                # digest_key: the ONE truncation — journal keys must stay
                # joinable against the gossiped `pfx` digest entries
                key=prefixlib.digest_key(key),
            )

    # -- lane-batched speculative serving (core.spec_batch) ------------------
    #
    # A speculating session is an ordinary engine lane: its target KV rows
    # ARE the lane's rows, so spec rounds interleave freely with regular
    # /forward decode batching on the same device. While speculation is
    # enabled, EVERY admission (spec or regular) is capped at
    # max_len - (k+1): the verify chunk writes k+1 rows at every lane's
    # frontier (garbage for non-participants), and a lane closer than that
    # to max_len would be clamp-corrupted (core.spec_batch headroom
    # contract). The node surfaces the reduced capacity as ordinary KV
    # overflow. The session-level drive (runner LRU, round coalescing,
    # deferred frees) is the shared SpecServing mixin; only the
    # lane-storage hooks live here.

    @property
    def _spec_mu(self):
        return self._mu

    def _spec_session_slot(self, session_id):
        return self._sessions.get(session_id)

    def _spec_session_len(self, session_id, lane):
        return self.engine.lengths[lane]

    def _spec_free_slot(self, session_id, lane):
        self.engine.lengths[lane] = 0
        self.engine.free.append(lane)

    def _spec_drop(self, session_id):
        self._drop(session_id)

    def _spec_new_runner(self, sampling):
        from inferd_tpu.core.spec_batch import LaneSpecRunner

        return LaneSpecRunner(
            self.cfg, self._spec["dcfg"], self._spec["k"], sampling=sampling
        )

    def _spec_plain_submit(self, lane, last_tok, session_id):
        return self._batcher.submit((lane, last_tok, None))

    def enable_spec(self, draft_layers: int, k: int) -> None:
        """Self-drafting lane speculation: the model's first `draft_layers`
        layers propose, the full stack verifies (layer-truncated self-draft,
        core.speculative.self_draft — one definition shared with the solo
        engine). Raises ValueError for structurally impossible configs
        (ring margin, layer counts); the caller logs and serves without."""
        from inferd_tpu.core import spec_batch
        from inferd_tpu.core.speculative import self_draft

        if self.pool is not None:
            raise ValueError(
                "lane speculation is not supported with paged KV yet "
                "(the verify chunk writes k+1 rows at every lane's "
                "frontier — a block-table write path for it is future "
                "work); serve --paged-kv without --spec-draft-layers"
            )
        if self.adapters is not None:
            raise ValueError(
                "lane speculation is not supported with the adapter "
                "registry yet (the layer-truncated self-draft would "
                "draft with the BASE model while the target verifies "
                "per-tenant weights — acceptance would collapse); serve "
                "--adapters without --spec-draft-layers"
            )
        if not 0 < draft_layers < self.cfg.num_layers:
            raise ValueError(
                f"draft_layers must be in (0, {self.cfg.num_layers})"
            )
        dcfg, dparams = self_draft(self.cfg, self.engine.params, draft_layers)
        spec_batch.check_ring_margin(self.cfg, dcfg, k)
        self._spec = {
            **self._spec_init(k, self.engine.lanes),
            "dcfg": dcfg,
            "dparams": dparams,
            "dcache": spec_batch.make_draft_cache(
                dcfg, self.engine.lanes, self.max_len
            ),
        }

    def spec_open(
        self, session_id: str, prompt_ids, sampling, seed: int = 0,
        parent: "str | None" = None, pin_len: int = 0,
        prefix_logits=None, want_lp: bool = False,
    ):
        """Claim a lane, prefill target + draft caches, return the first
        emitted token. The session stays marked in-flight until
        spec_close() — between rounds an idle lane must not be LRU-evicted
        by a concurrent admission. Raises CapacityError (no lane) or
        BufferError (prompt exceeds the spec-capped budget).

        `parent` + `pin_len` compose speculation with PREFIX CACHING: the
        lane forks the parent session's first pin_len KV slots (the same
        fork the regular loop uses), the target prefills only the suffix,
        and the DRAFT prefills the whole prompt (its layer-truncated cache
        has no pinned copy — a fraction of the saved target work). When
        the prompt IS the prefix, `prefix_logits` (the pin's stored
        last-token logits) seeds the first token. A fork miss raises
        SpecForkMiss — the caller falls back to a plain open or the
        regular loop."""
        import jax
        import jax.numpy as jnp

        sp = self._spec
        if sp is None:
            raise RuntimeError("speculation not enabled on this executor")
        n = len(prompt_ids)
        if n + 1 > self.cap:
            raise BufferError(
                f"prompt of {n} exceeds spec-capped capacity {self.cap}"
            )
        runner, batcher, rkey = self._spec_runner(sampling)
        forked = False
        if parent is not None and 0 < pin_len <= n:
            if not self.fork_session(session_id, parent, pin_len):
                raise SpecForkMiss(f"prefix fork from {parent} missed")
            forked = True
        with self._mu:
            if forked:
                # fork_session released _mu after claiming: re-validate the
                # un-inflight child wasn't LRU-evicted in the window
                if self._sessions.get(session_id) is None:
                    raise SpecForkMiss("forked lane evicted before open")
            if self._inflight.get(session_id):
                raise ValueError(f"session {session_id}: concurrent request")
            lane = self._lane_for(session_id, new_ok=not forked)
            if not forked and self.engine.lengths[lane]:
                self.engine.lengths[lane] = 0
                self._lane_hi[lane] = 0
            self._inflight[session_id] = 1
        try:
            start = pin_len if forked else 0
            suffix = list(prompt_ids[start:])
            b = min(bucket_len(n), self.max_len)
            padded = np.zeros((1, b), np.int32)
            padded[0, :n] = np.asarray(prompt_ids, np.int32)
            with self._dev_lock:
                if suffix:
                    sb = min(bucket_len(len(suffix)), self.max_len - start)
                    spad = np.zeros((1, sb), np.int32)
                    spad[0, : len(suffix)] = np.asarray(suffix, np.int32)
                    self.engine.cache, logits = self.engine._prefill_lane_logits(
                        self.engine.params, self.engine.cache,
                        jnp.asarray(spad), jnp.int32(lane), jnp.int32(start),
                        jnp.int32(len(suffix)),
                    )
                else:
                    if prefix_logits is None:
                        raise SpecForkMiss(
                            "prompt == pinned prefix but no stored logits"
                        )
                    logits = np.asarray(prefix_logits)
                # draft: always the FULL prompt from 0 (no pinned draft KV)
                sp["dcache"] = runner.draft_prefill(
                    sp["dparams"], sp["dcache"], padded, lane, 0, n
                )
                with self._mu:
                    self.engine.lengths[lane] = n
                    self._lane_hi[lane] = max(self._lane_hi.get(lane, 0), n)
                    sp["dlens"][lane] = n
            key, sub = jax.random.split(jax.random.PRNGKey(seed))
            first = runner.first_token(np.asarray(logits), sub)
            first_lp = (
                runner.row_lp(np.asarray(logits), first) if want_lp else None
            )
            with self._mu:
                sp["sid"][session_id] = (runner, batcher, rkey, want_lp)
                sp["keys"][session_id] = key
                sp["count"][rkey] = sp["count"].get(rkey, 0) + 1
            return first, first_lp
        except Exception:
            with self._mu:
                self._inflight.pop(session_id, None)
                self._drop(session_id)
            raise

    def _run_spec_batch(self, runner, entries) -> None:
        """Spec-batcher flush: ONE coalesced round for every waiting lane
        (window.py calls this with no locks held)."""
        sp = self._spec
        L = self.engine.lanes
        with self._dev_lock:
            active = np.zeros((L,), bool)
            last = np.zeros((L,), np.int32)
            catch = np.zeros((L,), np.int32)
            catch_mask = np.zeros((L,), bool)
            keys = np.zeros((L, 2), np.uint32)
            sampled = runner.sampling.temperature > 0.0
            with self._mu:
                dlens = np.asarray(sp["dlens"], np.int32)
                wants = {}
                for e in entries:
                    lane, sid, lt, pt, sub = e.payload
                    active[lane] = True
                    last[lane] = lt
                    ent = sp["sid"].get(sid)
                    wants[lane] = bool(ent and ent[3])
                    if sp["dlens"][lane] < self.engine.lengths[lane]:
                        catch[lane] = pt
                        catch_mask[lane] = True
                    if sampled:
                        keys[lane] = sub
            want_flush = any(wants.values())
            res = runner.run_round(
                self.engine.params, sp["dparams"], self.engine, sp["dcache"],
                last, catch, catch_mask, dlens, active,
                keys if sampled else None, want_lp=want_flush,
            )
            if want_flush:
                toks, n_new, dcache, lps, tis, tls = res
            else:
                toks, n_new, dcache = res
            sp["dcache"] = dcache
            with self._mu:
                for e in entries:
                    lane, sid, _, _, _ = e.payload
                    n = int(n_new[lane])
                    old = self.engine.lengths[lane]
                    self.engine.lengths[lane] = old + n
                    sp["dlens"][lane] = old + min(n, runner.k)
                    self._lane_hi[lane] = max(
                        self._lane_hi.get(lane, 0), old + runner.k + 1
                    )
                    e.result = self._spec_entry_result(
                        wants.get(lane), toks[lane], n,
                        lps[lane] if want_flush else None,
                        tis[lane] if want_flush else None,
                        tls[lane] if want_flush else None,
                    )

    # -- lane/session bookkeeping (call under self._mu) ----------------------

    def _lane_for(self, session_id: str, new_ok: bool, protect=()) -> int:
        lane = self._sessions.get(session_id)
        if lane is not None:
            self._last_used[session_id] = time.monotonic()
            return lane
        if not new_ok:
            raise ValueError(
                f"session {session_id}: unknown session resumed mid-stream "
                "(cache evicted or node restarted)"
            )
        if not self.engine.free:
            # LRU-evict a session with NO request in flight (neither waiting
            # in the decode batch nor mid-prefill on another thread);
            # `protect` shields a fork's parent from being its own victim
            victims = [
                s
                for s in self._sessions
                if not self._inflight.get(s) and s not in protect
            ]
            if not victims:
                raise CapacityError("all lanes busy with in-flight requests")
            oldest = min(victims, key=lambda s: self._last_used.get(s, 0.0))
            emit_safely(
                self.on_event, "lane.evict", session=oldest,
                lane=self._sessions.get(oldest),
                idle_s=round(
                    time.monotonic() - self._last_used.get(oldest, 0.0), 3
                ),
                claimant=session_id,
            )
            self._drop(oldest)
        lane = self.engine.free.pop()
        self._sessions[session_id] = lane
        self._last_used[session_id] = time.monotonic()
        self._lane_hi[lane] = 0  # fresh claimant: old marks are meaningless
        return lane

    def _drop(self, session_id: str) -> None:
        lane = self._sessions.pop(session_id, None)
        self._last_used.pop(session_id, None)
        self._release_adapter_locked(session_id)
        if lane is None:
            return
        # invalidate decode entries still waiting in the batch window — a
        # later flusher step must never write this lane on the old
        # session's behalf once a new session may own it
        self._batcher.invalidate(
            lambda payload, _lane=lane: payload[0] == _lane,
            ValueError(f"session {session_id} ended mid-request"),
        )
        if self._inflight.get(session_id):
            # a request is mid-device-step (e.g. swapped into a flusher
            # batch): defer the free until it drains, else a new claimant
            # would share the lane with the stale write
            self._dying[lane] = session_id
        else:
            self._free_lane(lane)

    def _free_lane(self, lane: int) -> None:
        """Return a lane to the free list (under self._mu). Paged: the
        chain frees per-block — cached/pinned prefix blocks survive via
        their index references."""
        self.engine.lengths[lane] = 0
        self._lane_slot[lane] = 0  # back to the base adapter
        if self.pool is not None:
            self.pool.release_lane(lane)
        self.engine.free.append(lane)

    # -- executor contract ---------------------------------------------------

    def process(self, session_id: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        toks = np.asarray(payload["tokens"], dtype=np.int32)
        if toks.ndim != 2 or toks.shape[0] != 1:
            raise ValueError(f"batched stage expects tokens [1, S], got {toks.shape}")
        start_pos = int(payload.get("start_pos", 0))
        real_len = int(payload.get("real_len", toks.shape[1]))

        acquired = self._resolve_adapter(session_id, payload, start_pos)
        try:
            return self._process_inner(
                session_id, payload, toks, start_pos, real_len, acquired
            )
        except Exception:
            # an admission that died before _bind_adapter_locked consumed
            # the reference must give it back, or the slot leaks a
            # refcount and can never be evicted
            if acquired is not None and acquired[1]:
                self.adapters.release(acquired[0])
            raise

    def _process_inner(self, session_id: str, payload: Dict[str, Any],
                       toks, start_pos: int, real_len: int, acquired):
        with self._mu:
            if self._inflight.get(session_id):
                # a duplicate/replayed request racing the original would
                # pass the frontier check and double-advance the lane
                raise ValueError(
                    f"session {session_id}: concurrent request (one step at "
                    "a time per session)"
                )
            lane = self._lane_for(session_id, new_ok=start_pos == 0)
            owner = f"session {session_id}, lane {lane}"
            have = self.engine.lengths[lane]
            if start_pos == 0 and have:
                # session restart under the same id: reset the lane
                self.engine.lengths[lane] = 0
                self._lane_hi[lane] = 0
                if self.pool is not None:
                    self.pool.release_lane(lane)
                have = 0
            if start_pos + real_len > self.cap:
                # overflow is checked BEFORE any frontier mutation: a
                # rejected oversized replay must not leave the lane rolled
                # back with nothing recomputed. `cap` < max_len while
                # speculation is enabled (verify-chunk headroom: EVERY lane
                # must stay k+1 short of the physical buffer).
                raise BufferError(
                    f"session {session_id}: KV overflow "
                    f"({start_pos}+{real_len} > {self.cap})"
                )
            if start_pos != have:
                if not 0 < start_pos < have:
                    raise ValueError(
                        f"session {session_id}: start_pos {start_pos} != cache "
                        f"length {have} (out-of-order chunk)"
                    )
                hi = max(self._lane_hi.get(lane, 0), have)
                if (
                    self.engine.cache.k_loc is not None
                    and hi - start_pos > RING_MARGIN
                ):
                    raise ValueError(
                        f"session {session_id}: replay rollback to "
                        f"{start_pos} exceeds the ring margin (high-water "
                        f"mark {hi})"
                    )
                # deterministic chunk REPLAY (client re-sent after a lost
                # response): roll the lane's frontier back and recompute —
                # identical KV; ring lanes stay exact while the HIGH-WATER
                # mark is within the margin (the same contract as the stage
                # executor's replay path). Preserve the pre-rollback
                # frontier as the mark: hi only diverges from the length
                # across rollbacks.
                self._lane_hi[lane] = hi
                self.engine.lengths[lane] = start_pos
                if self.pool is not None:
                    # a replay rewrite into a SHARED region splits those
                    # blocks copy-on-write first — the recompute must not
                    # scribble on blocks other lanes / the prefix index
                    # still read (copies apply at the next dispatch)
                    before = self.pool.cow_splits
                    self.pool.make_writable(lane, start_pos, owner=owner)
                    if self.pool.cow_splits != before:
                        emit_safely(
                            self.on_event, "kv.cow_split",
                            session=session_id, lane=lane,
                            from_pos=start_pos,
                            blocks=self.pool.cow_splits - before,
                        )
            if self.pool is not None and real_len == 1 and start_pos > 0:
                # decode dispatches write positions [start_pos,
                # start_pos + K): the chain must cover them before the jit
                # scatters (prefill ensures per chunk instead)
                k_req = max(1, min(int(payload.get("decode_steps") or 0),
                                   self.cap - start_pos))
                self.pool.ensure(lane, start_pos + k_req, owner=owner)
            self._bind_adapter_locked(session_id, lane, start_pos, acquired)
            self._inflight[session_id] = 1

        try:
            if real_len == 1 and start_pos > 0:
                from inferd_tpu.runtime.executor import parse_kstep

                ks = parse_kstep(payload, self.cap - start_pos)
                if ks is not None:
                    # multi-step fused decode: K on-device-sampled tokens
                    # per dispatch; co-arrived K-step lanes fuse into one
                    # K-step scan (see _run_decode_batch)
                    res = self._decode_batched(
                        session_id, lane, int(toks[0, 0]), ks
                    )
                    return {**res, "start_pos": start_pos}
                logits = self._decode_batched(session_id, lane, int(toks[0, 0]))
                saved = 0
            else:
                logits, saved = self._prefill_solo(
                    session_id, lane, toks, start_pos, real_len
                )
        finally:
            with self._mu:
                self._inflight.pop(session_id, None)
                if self._dying.get(lane) == session_id:  # ended mid-request
                    del self._dying[lane]
                    self._free_lane(lane)
        return {
            "logits": logits[None, :],
            "real_len": real_len,
            "start_pos": start_pos,
            # per-request shared-prefix saving (stage_batch contract):
            # span attr + kv.saved_tokens at the node, stripped before
            # the reply; omitted on cold prefills
            **({"tokens_saved": saved} if saved else {}),
        }

    def _sync_paged(self):
        """core.cache.sync_paged over this executor's state: call under
        self._dev_lock; rebinds engine.cache (the copy jit donates)."""
        self.engine.cache = sync_paged(
            self.pool, self.engine.cache, self.engine._copy_blocks,
            self._mu,
        )
        return self.engine.cache

    def _prefill_solo(self, session_id: str, lane: int, toks: np.ndarray,
                      start: int, n: int):
        """Prompt ingestion: shared-prefix skip (paged — full blocks whose
        chained token hash is cached/pinned map read-only, zero prefill
        FLOPs for the shared region), then `prefill_chunk`-token
        dispatches with the device lock RELEASED between chunks so decode
        windows interleave, then prefix registration (paged) so later
        sessions skip what this one computed."""
        import jax.numpy as jnp

        owner = f"session {session_id}, lane {lane}"
        pos = start
        keys = None
        saved = 0
        with self._mu:
            ad_name = self._session_adapter.get(session_id)
            ads = self._ads([self._lane_slot[lane]])
        if self.pool is not None and start == 0:
            ids = [int(t) for t in toks[0, :n]]
            # adapter sessions salt the chain: their KV depends on the
            # adapter weights, so tenants must never share prefix blocks
            # across adapters (one tenant's sessions still do)
            keys = prefixlib.block_keys(
                ids, self.pool.block_size, salt=ad_name
            )
            # map at most the blocks covering n - 1 tokens: the LAST
            # prompt token always computes (its logits are the response)
            nmap = (n - 1) // self.pool.block_size
            with self._mu:
                cov = self.pool.map_prefix(lane, keys[:nmap])
            if cov:
                pos = saved = cov
                with self._mu:
                    self.engine.lengths[lane] = cov
                    self._lane_hi[lane] = max(self._lane_hi.get(lane, 0), cov)
                emit_safely(
                    self.on_event, "prefix.hit", session=session_id,
                    lane=lane, tokens=cov,
                )
        end = start + n
        step = self.prefill_chunk if self.prefill_chunk > 0 else end - pos
        logits = None
        while pos < end:
            c = min(step, end - pos)
            # cap the padded bucket so the in-jit dynamic_update_slice can
            # never clamp into older slots near the end of the cache (the
            # stage executor's _cache_for guards the same invariant); a
            # capped tail shape compiles its own program — rare and bounded
            b = min(bucket_len(c), self.max_len - pos)
            padded = np.zeros((1, b), np.int32)
            padded[0, :c] = toks[0, pos - start: pos - start + c]
            if self.pool is not None:
                with self._mu:
                    self.pool.ensure(lane, pos + c, owner=owner)
            with self._dev_lock:
                if self.pool is not None:
                    cache = self._sync_paged()
                    self.engine.cache, logits = (
                        self.engine._prefill_lane_logits_paged(
                            self.engine.params, cache, jnp.asarray(padded),
                            jnp.asarray(self.pool.table[lane:lane + 1]),
                            jnp.int32(pos), jnp.int32(c), ads=ads,
                        )
                    )
                else:
                    self.engine.cache, logits = (
                        self.engine._prefill_lane_logits(
                            self.engine.params, self.engine.cache,
                            jnp.asarray(padded),
                            jnp.int32(lane), jnp.int32(pos), jnp.int32(c),
                            ads=ads,
                        )
                    )
                # advance the lane BEFORE releasing the device lock: a
                # flusher snapshots lengths under the same lock order
                # (_dev_lock, _mu), so it can never scatter a decode write
                # over these fresh rows at the stale position
                with self._mu:
                    self.engine.lengths[lane] = pos + c  # real tokens only
                    self.prefill_tokens += c
            pos += c
            if self.prefill_chunk > 0 and pos < end:
                # explicit yield between chunks: threading.Lock is NOT
                # fair — without this, the chunk loop can re-acquire the
                # device before a waiting decode flusher ever wakes, and
                # chunking would bound nothing. Sub-ms: noise next to a
                # chunk dispatch. The ticketed FairDeviceLock grants in
                # arrival order, so there the yield is dead weight.
                if not lockwatch.is_fair(self._dev_lock):
                    time.sleep(0.0005)
        if self.pool is not None and keys:
            with self._mu:
                self.pool.register_prefix(lane, keys)
        # ONE boundary transfer: only the LAST chunk's logits are the
        # response — mid-chunk logits never leave the device
        return np.asarray(logits, np.float32), saved

    def _decode_batched(self, session_id: str, lane: int, token: int, ks=None):
        return self._batcher.submit((lane, token, ks))

    def _run_decode_batch(self, entries) -> None:
        """Flush callback: ONE batched device step for every waiting lane
        (runtime/window.py calls this with no locks held).

        Entries partition into the classic logits contract (client-side
        sampling, one token per dispatch) and multi-step fused decode
        (`ks` payload from parse_kstep: K on-device-sampled tokens per
        dispatch). K-step entries sharing a sampling config fuse into ONE
        K-step scan (models/qwen3.decode_k via the engine's
        _decode_k_serve) with K = the group's minimum budget-clamped
        request — co-batched lanes decode K steps per window when every
        lane has >= K budget, degrading toward K=1 at boundaries. A lane
        whose `eos` fires mid-window deactivates in-graph; its result
        carries only the really-committed tokens.

        Failure isolation is per DISPATCH: a window can run one legacy
        step plus several K-step group scans, and a raising dispatch must
        not clobber results another dispatch already committed (lengths
        advanced, e.result set) — each dispatch marks only ITS entries
        failed and the flush returns normally, so submit() raises for
        exactly the sessions whose device step died. Isolation holds for
        HOST-side failures (the cache untouched); a device-side failure
        after the jit donated the cache invalidates the shared buffers,
        so the window stops dispatching and fails the remaining entries
        with a clear error (executor.cache_intact) — committed results
        still stand."""
        import jax.numpy as jnp

        from inferd_tpu.runtime.executor import (
            cache_intact, fuse_kstep_group, kstep_hi,
        )

        legacy = [e for e in entries if e.payload[2] is None]
        kstep = [e for e in entries if e.payload[2] is not None]
        poisoned: Optional[Exception] = None
        with self._dev_lock:
            if legacy:
                try:
                    with self._mu:
                        lens = list(self.engine.lengths)  # snapshot under _mu
                        ids = list(self._lane_slot)
                    ads = self._ads(ids)
                    toks = [0] * self.engine.lanes
                    active = [False] * self.engine.lanes
                    for e in legacy:
                        lane, token, _ks = e.payload
                        toks[lane] = token
                        active[lane] = True
                    if self.pool is not None:
                        self.engine.cache, logits = (
                            self.engine._decode_logits_paged(
                                self.engine.params, self._sync_paged(),
                                jnp.asarray(toks, jnp.int32),
                                jnp.asarray(lens, jnp.int32),
                                jnp.asarray(active),
                                ads=ads,
                            )
                        )
                    else:
                        self.engine.cache, logits = self.engine._decode_logits(
                            self.engine.params, self.engine.cache,
                            jnp.asarray(toks, jnp.int32),
                            jnp.asarray(lens, jnp.int32),
                            ads=ads,
                        )
                    out = np.asarray(logits, np.float32)
                    with self._mu:
                        for e in legacy:
                            self.engine.lengths[e.payload[0]] += 1
                    for e in legacy:
                        e.result = out[e.payload[0]]
                except Exception as exc:
                    for e in legacy:
                        e.error = exc
                    # the window flush counts every live entry as served
                    # AFTER this callback returns; net failed entries to
                    # zero so /stats batched_tokens stays token-true
                    self._batcher.n_served -= len(legacy)
                    if not cache_intact(self.engine.cache):
                        poisoned = exc
            groups: Dict[tuple, list] = {}
            for e in kstep:
                groups.setdefault(e.payload[2]["sampling"], []).append(e)
            for _sampling, grp in groups.items():
                if poisoned is not None:
                    # a donated-cache dispatch died device-side: the KV
                    # buffers are gone, dispatching would only raise a
                    # deleted-buffer error — fail the rest clearly
                    for e in grp:
                        e.error = RuntimeError(
                            "KV cache invalidated by an earlier dispatch "
                            f"failure in this window: {poisoned}"
                        )
                    self._batcher.n_served -= len(grp)  # see legacy note
                    continue
                try:
                    with self._mu:
                        lens = list(self.engine.lengths)
                        ids = list(self._lane_slot)
                    kg, seq, n_new, nkeys, self.engine.cache = (
                        fuse_kstep_group(
                            self.engine._decode_k_serve, self.engine.params,
                            self._sync_paged() if self.pool is not None
                            else self.engine.cache,
                            lens, self.engine.lanes,
                            [e.payload for e in grp],
                            ads=self._ads(ids),
                        )
                    )
                    with self._mu:
                        for e in grp:
                            lane = e.payload[0]
                            n = int(n_new[lane])  # jaxlint: disable=J003 -- n_new is a HOST array (fuse_kstep_group materialized it)
                            old = self.engine.lengths[lane]
                            self.engine.lengths[lane] = old + n
                            self._lane_hi[lane] = max(
                                self._lane_hi.get(lane, 0),
                                kstep_hi(old, n, kg),
                            )
                    served_tokens = 0
                    for e in grp:
                        lane = e.payload[0]
                        n = int(n_new[lane])  # jaxlint: disable=J003 -- host array
                        served_tokens += n
                        e.result = {
                            "tokens": [seq[:n, lane].tolist()],  # jaxlint: disable=J003 -- host array row unpack, no device sync
                            "real_len": n,
                            "decode_steps": kg,
                            "key": nkeys[lane].tolist(),  # jaxlint: disable=J003 -- host array row unpack, no device sync
                        }
                    # token-true stats: the window flush loop counts one
                    # served unit per ENTRY; a K-step entry really served
                    # n tokens — /stats batched_tokens and mean_batch
                    # must reflect tokens, not dispatches
                    self._batcher.n_served += served_tokens - len(grp)
                except Exception as exc:
                    for e in grp:
                        e.error = exc
                    self._batcher.n_served -= len(grp)  # see legacy note
                    if not cache_intact(self.engine.cache):
                        poisoned = exc

    def end_session(self, session_id: str) -> None:
        with self._mu:
            self._drop(session_id)

    def fork_session(
        self, new_session_id: str, parent_session_id: str, prefix_len: int
    ) -> bool:
        """Seed a new session's lane with the parent lane's first
        `prefix_len` KV slots (prefix caching on the batched path). False on
        any miss — unknown/short parent, no claimable lane — and the caller
        falls back to a full prefill.

        Paged mode maps the parent's full blocks READ-ONLY into the child
        (refcounted, CoW on divergence) and queues a private copy of only
        the partial tail block — O(1) device work instead of a prefix-
        sized buffer copy."""
        if prefix_len <= 0:
            return False
        with self._mu:
            if self._session_adapter.get(parent_session_id):
                # the fork flow admits the child WITHOUT an adapter key:
                # decoding adapter-built KV with the base adapter would
                # diverge silently — the clean False re-prefills instead
                return False
        if self.pool is not None:
            with self._mu:
                plane = self._sessions.get(parent_session_id)
                if (
                    plane is None
                    or self.engine.lengths[plane] < prefix_len
                    or new_session_id in self._sessions
                ):
                    return False
                try:
                    lane = self._lane_for(
                        new_session_id, new_ok=True,
                        protect=(parent_session_id,),
                    )
                except CapacityError:
                    return False
                try:
                    self.pool.fork_lane(
                        plane, lane, prefix_len,
                        owner=f"session {new_session_id}, lane {lane}",
                    )
                except BufferError:
                    self._drop(new_session_id)
                    return False
                self.engine.lengths[lane] = prefix_len
                self._lane_hi[lane] = prefix_len
            return True
        with self._dev_lock:  # lock order matches _prefill_solo
            with self._mu:
                plane = self._sessions.get(parent_session_id)
                if (
                    plane is None
                    or self.engine.lengths[plane] < prefix_len
                    or new_session_id in self._sessions
                ):
                    return False
                parent_hi = max(
                    self._lane_hi.get(plane, 0), self.engine.lengths[plane]
                )
                if (
                    self.engine.cache.k_loc is not None
                    and parent_hi - prefix_len > RING_MARGIN
                ):
                    # ring KV: the parent ran past the margin since the fork
                    # point — its sliding-layer rings hold slots whose stale
                    # data would alias into the child's windows (same guard
                    # as the stage executor's fork_session)
                    return False
                try:
                    lane = self._lane_for(
                        new_session_id, new_ok=True,
                        protect=(parent_session_id,),
                    )
                except CapacityError:
                    return False
                # mark the child in flight: between here and the length
                # write below, _mu is released while the device copy runs —
                # an un-inflight child could be LRU-evicted by a concurrent
                # claim and its lane handed to another session mid-fork
                self._inflight[new_session_id] = 1
            try:
                m = min(bucket_len(prefix_len), self.max_len)
                self.engine.fork_lane(plane, lane, m)
                with self._mu:
                    self.engine.lengths[lane] = prefix_len
                    # the child's rings carry the parent's stale slots:
                    # use the parent_hi validated under the SAME _mu hold
                    # as the margin check (a re-read here would race a
                    # parent restart/eviction resetting its mark while the
                    # device copy still took the OLD ring content)
                    self._lane_hi[lane] = parent_hi
            finally:
                with self._mu:
                    self._inflight.pop(new_session_id, None)
                    if self._dying.get(lane) == new_session_id:
                        # ended mid-fork (end_session deferred the free)
                        del self._dying[lane]
                        self._free_lane(lane)
        return True

    def export_sessions(self, only: "str | None" = None):
        """Snapshot live sessions' lane KV for migration/shutdown handoff
        (the shared runtime/handoff schema), so runtime/node.py's
        _export_and_handoff and /import_session work unchanged for
        --batch-lanes replicas. `only` exports a single session (the
        deliberate prefill->decode handoff path)."""
        out = []
        with self._dev_lock:  # quiesce the device first
            if self.pool is not None:
                # apply queued CoW copies BEFORE reading the pools: a
                # session forked/rolled-back since the last dispatch still
                # has its private-copy blocks pending — exporting through
                # the repointed table would ship uninitialized blocks
                self._sync_paged()
            self._export_locked(out, only)
        return out

    def _export_locked(self, out, only) -> None:
        from inferd_tpu.runtime import handoff

        with self._mu:
            for sid, lane in list(self._sessions.items()):
                if only is not None and sid != only:
                    continue
                n = self.engine.lengths[lane]
                if n == 0:
                    continue
                if self.pool is not None:
                    # dense materialization through the block table, ONE
                    # device gather per session's chain (never a whole-pool
                    # host pull — the pool is fleet capacity, the session
                    # is a handful of blocks); the wire schema stays the
                    # dense one, so paged/dense replicas interchange
                    # sessions freely
                    nb = self.pool.blocks_for(n)
                    chain = self.pool.table[lane, :nb]
                    cache = self.engine.cache
                    kd = np.asarray(cache.k[:, chain])
                    vd = np.asarray(cache.v[:, chain])
                    layers = kd.shape[0]
                    kd = kd.reshape(
                        layers, nb * self.pool.block_size, *kd.shape[3:]
                    )[:, None, :n]
                    vd = vd.reshape(
                        layers, nb * self.pool.block_size, *vd.shape[3:]
                    )[:, None, :n]
                    out.append((sid, self._stamp_adapter(
                        sid, handoff.encode(kd, vd, n, None, None, None)
                    )))
                    continue
                kl = vl = hi = None
                if self.engine.cache.k_loc is not None:
                    kl = np.asarray(self.engine.cache.k_loc[:, lane : lane + 1])
                    vl = np.asarray(self.engine.cache.v_loc[:, lane : lane + 1])
                    hi = max(self._lane_hi.get(lane, 0), n)
                out.append((sid, self._stamp_adapter(sid, handoff.encode(
                    np.asarray(self.engine.cache.k[:, lane : lane + 1, :n]),
                    np.asarray(self.engine.cache.v[:, lane : lane + 1, :n]),
                    n, kl, vl, hi,
                ))))

    def _stamp_adapter(self, sid: str, payload: Dict[str, Any]):
        """Ride the session's adapter binding on its handoff payload
        (caller holds self._mu): the importer/standby must rebind the
        tenant's adapter or DECLINE — an adopted tenant session silently
        resuming on the base weights would be exactly the tenant
        corruption the admission path rejects loudly. Base sessions gain
        no key (payloads byte-identical to pre-adapter)."""
        name = self._session_adapter.get(sid)
        if name is not None:
            payload["adapter"] = name
        return payload

    def session_lengths(self) -> Dict[str, int]:
        """{session_id: committed KV length} — the cheap frontier surface
        the standby replicator polls (runtime/repl.SessionReplicator)."""
        with self._mu:
            return {
                sid: int(self.engine.lengths[lane])
                for sid, lane in self._sessions.items()
                if self.engine.lengths[lane] > 0
            }

    def export_session_delta(self, session_id: str, since: int):
        """Incremental flavor of export_sessions for standby replication
        (handoff schema + a "start" key; None = nothing new). PAGED
        lanes ship exactly the IMMUTABLE FULL BLOCKS past the frontier —
        the partial tail block is still being written and re-ships once
        it fills, so the standby's RPO is bounded by block_size on top
        of the tick interval. Dense lanes ship the slab delta directly
        (rings whole, like the stage executor's sibling)."""
        from inferd_tpu.runtime import handoff
        from inferd_tpu.runtime.repl import START_KEY

        since = max(0, int(since))
        # cheap nothing-to-ship early-out under _mu alone: the common
        # replication tick (every resident session, every interval) must
        # not contend on the decode hot path's device lock just to
        # discover no block/slot completed since the last ship
        with self._mu:
            lane = self._sessions.get(session_id)
            if lane is None:
                return None
            n = int(self.engine.lengths[lane])
            if self.pool is not None:
                bs = self.pool.block_size
                if (n // bs) * bs <= (since // bs) * bs:
                    return None
            elif n <= since:
                return None
        with self._dev_lock:
            if self.pool is not None:
                self._sync_paged()  # queued CoW copies must land first
            with self._mu:
                lane = self._sessions.get(session_id)
                if lane is None:
                    return None
                n = int(self.engine.lengths[lane])
                if self.pool is not None:
                    bs = self.pool.block_size
                    if since % bs:
                        # a foreign frontier (e.g. adopted mid-stream from
                        # a dense peer): restart block-aligned
                        since = (since // bs) * bs
                    end = (n // bs) * bs
                    if end <= since:
                        return None
                    chain = self.pool.table[lane, since // bs: end // bs]
                    cache = self.engine.cache
                    # one device gather of just this session's new blocks
                    # (never a whole-pool host pull — export_sessions'
                    # discipline): [L, nb, bs, ...] -> [L, 1, nb*bs, ...]
                    kd = np.asarray(cache.k[:, chain])
                    vd = np.asarray(cache.v[:, chain])
                    layers = kd.shape[0]
                    kd = kd.reshape(layers, end - since, *kd.shape[3:])[:, None]
                    vd = vd.reshape(layers, end - since, *vd.shape[3:])[:, None]
                    payload = self._stamp_adapter(
                        session_id,
                        handoff.encode(kd, vd, end, None, None, None),
                    )
                    payload[START_KEY] = since
                    return payload
                if n <= since:
                    return None
                kl = vl = hi = None
                if self.engine.cache.k_loc is not None:
                    kl = np.asarray(self.engine.cache.k_loc[:, lane: lane + 1])
                    vl = np.asarray(self.engine.cache.v_loc[:, lane: lane + 1])
                    hi = max(self._lane_hi.get(lane, 0), n)
                payload = self._stamp_adapter(session_id, handoff.encode(
                    np.asarray(self.engine.cache.k[:, lane: lane + 1, since:n]),
                    np.asarray(self.engine.cache.v[:, lane: lane + 1, since:n]),
                    n, kl, vl, hi,
                ))
                payload[START_KEY] = since
                return payload

    def import_session(self, session_id: str, payload: Dict[str, Any]) -> bool:
        """Adopt a migrated session into a free lane (same-model batched
        replicas; schema/shape mismatches reject cleanly — the shared
        runtime/handoff validator fails closed BEFORE a lane is claimed)."""
        import jax.numpy as jnp

        from inferd_tpu.core.cache import KVCache
        from inferd_tpu.runtime import handoff

        ring = self.engine.cache.k_loc is not None
        # validate against the spec-capped capacity: an imported session
        # longer than cap would break the verify-chunk headroom contract
        dec = handoff.decode(
            payload, self.cfg, self.cfg.num_layers, 0, self.cap,
            want_ring=ring,
        )
        if dec is None:
            return False
        # a tenant session's KV was built WITH its adapter: rebind here
        # (hot-loading if needed — before any executor lock) or DECLINE,
        # so the session lands on a replica that can serve it instead of
        # silently continuing on the base weights. The fail-closed False
        # degrades to the client's full restart, whose first chunk
        # re-states the adapter key.
        ad_name = payload.get("adapter")
        if ad_name is not None:
            if self.adapters is None:
                return False
            try:
                self.adapters.acquire(str(ad_name))
            except Exception:
                return False
            ad_name = str(ad_name)
        k, v, n = dec["k"], dec["v"], dec["n"]
        k_loc, v_loc = dec["k_loc"], dec["v_loc"]
        if self.pool is not None:
            # _import_paged owns the acquired reference from here: its
            # early declines release it, its post-bind rollbacks release
            # through _drop
            return self._import_paged(session_id, k, v, n, ad_name)
        with self._dev_lock, self._mu:
            if session_id in self._sessions:
                if ad_name is not None:
                    self.adapters.release(ad_name)
                return False
            try:
                lane = self._lane_for(session_id, new_ok=True)
            except CapacityError:
                if ad_name is not None:
                    self.adapters.release(ad_name)
                return False
            if ad_name is not None:
                # bound BEFORE the risky device writes: the rollback
                # path's _drop releases the reference with the session
                self._session_adapter[session_id] = ad_name
                self._lane_slot[lane] = self.adapters.slot_of(ad_name)
            try:
                t = min(k.shape[2], self.max_len)
                cache = self.engine.cache
                nk = cache.k.at[:, lane, :t].set(
                    jnp.asarray(k[:, 0, :t], cache.k.dtype)
                )
                nv = cache.v.at[:, lane, :t].set(
                    jnp.asarray(v[:, 0, :t], cache.v.dtype)
                )
                nkl, nvl = cache.k_loc, cache.v_loc
                if k_loc is not None:
                    nkl = cache.k_loc.at[:, lane].set(
                        jnp.asarray(k_loc[:, 0], cache.k_loc.dtype)
                    )
                    nvl = cache.v_loc.at[:, lane].set(
                        jnp.asarray(v_loc[:, 0], cache.v_loc.dtype)
                    )
                self.engine.cache = KVCache(
                    k=nk, v=nv, length=cache.length, k_loc=nkl, v_loc=nvl
                )
            except Exception:
                # rollback: a half-adopted session must not pin the lane
                # (the mesh path has the same guard)
                self._drop(session_id)
                return False
            self.engine.lengths[lane] = n
            self._lane_hi[lane] = dec["hi"]
        return True

    def _import_paged(self, session_id: str, k, v, n: int,
                      ad_name: "str | None" = None) -> bool:
        """Adopt a migrated session into pool blocks: allocate a chain,
        reshape the dense [L, 1, n, ...] snapshot into block granularity,
        scatter it into the pools in one update. `ad_name`: the tenant
        adapter the caller already acquire()d — bound to the lane on
        claim (so _drop rollbacks release it), released here on the
        pre-claim declines."""
        import jax.numpy as jnp

        with self._dev_lock, self._mu:
            if session_id in self._sessions:
                if ad_name is not None:
                    self.adapters.release(ad_name)
                return False
            try:
                lane = self._lane_for(session_id, new_ok=True)
            except CapacityError:
                if ad_name is not None:
                    self.adapters.release(ad_name)
                return False
            if ad_name is not None:
                self._session_adapter[session_id] = ad_name
                self._lane_slot[lane] = self.adapters.slot_of(ad_name)
            try:
                self.pool.ensure(
                    lane, n, owner=f"session {session_id}, lane {lane}"
                )
            except BufferError:
                self._drop(session_id)
                return False
            try:
                bs = self.pool.block_size
                nb = self.pool.blocks_for(n)
                pad = [(0, 0), (0, nb * bs - n), (0, 0), (0, 0)]
                layers = k.shape[0]
                kp = np.pad(k[:, 0, :n], pad).reshape(
                    layers, nb, bs, *k.shape[3:]
                )
                vp = np.pad(v[:, 0, :n], pad).reshape(
                    layers, nb, bs, *v.shape[3:]
                )
                chain = jnp.asarray(self.pool.table[lane, :nb])
                cache = self.engine.cache
                dt = cache.k.dtype
                self.engine.cache = type(cache)(
                    k=cache.k.at[:, chain].set(jnp.asarray(kp, dt)),
                    v=cache.v.at[:, chain].set(jnp.asarray(vp, dt)),
                    table=cache.table, length=cache.length,
                )
            except Exception:
                self._drop(session_id)
                return False
            self.engine.lengths[lane] = n
            self._lane_hi[lane] = n
        return True

    # -- prefix caching (paged mode) -----------------------------------------

    def pin_prefix(self, prefix_ids) -> int:
        """Prefill `prefix_ids` once into pool blocks and PIN them
        (resident until unpinned; later sessions map the region read-only
        instead of recomputing it) — the Engine pin store generalized to
        refcounted pool blocks. Returns the pinned token coverage."""
        if self.pool is None:
            raise ValueError("pin_prefix needs paged KV (--paged-kv)")
        ids = [int(t) for t in prefix_ids]
        if not ids:
            raise ValueError("prefix ids must be non-empty")
        keys = prefixlib.block_keys(ids, self.pool.block_size)
        sid = "__pin__" + (keys[-1].hex() if keys else "short")
        self.process(sid, {
            "tokens": [ids], "start_pos": 0, "real_len": len(ids),
        })
        with self._mu:
            self.pool.pin(keys)
        self.end_session(sid)
        return len(keys) * self.pool.block_size

    def unpin_prefix(self, prefix_ids) -> None:
        if self.pool is None:
            return
        with self._mu:
            self.pool.unpin(prefixlib.block_keys(
                [int(t) for t in prefix_ids], self.pool.block_size
            ))

    def block_stats(self) -> "Dict[str, Any] | None":
        """Block-pool gauges for obs.devtel (None on the dense layout)."""
        if self.pool is None:
            return None
        with self._mu:
            return self.pool.block_stats()

    def prefix_digest(self) -> "Dict[str, Any] | None":
        """Gossip-ready digest of the pool's hot prefix index
        (core.prefix.make_digest; the stage_batch contract) — the
        whole-model executor always has token-keyed prefixes, so only
        dense mode and an empty index return None (key omitted from
        gossip, never an empty decoy)."""
        if self.pool is None:
            return None
        with self._mu:
            keys = self.pool.digest_keys(prefixlib.DIGEST_GOSSIP_KEYS)
            bs = self.pool.block_size
        if not keys:
            return None
        return prefixlib.make_digest(keys, bs)

    def anatomy_target(self) -> Dict[str, Any]:
        """Live step-anatomy inputs for the continuous profiling plane
        (obs.prof.LiveAnatomy): this executor's REAL serving weights
        (already quantized/LoRA-merged at load) and paged/dense cache
        config, with ctx tracking the current decode frontier — rounded
        UP to a 64-token bucket so the scan shapes (and their XLA
        compilations) stay stable as the frontier drifts token by token.
        Whole-model executor: every device phase applies."""
        with self._mu:
            ctx = max(self.engine.lengths, default=0)
        ctx = -(-max(ctx, 32) // 64) * 64  # 64-token shape bucket
        return {
            "cfg": self.cfg,
            "params": self.engine.params,
            "phases": (
                "embed", "attention", "mlp", "lm_head", "sampling",
                "kv_write",
            ),
            "ctx": min(ctx, max(self.max_len - 64, 32)),
            "batch": 1,
            "paged_block_size": (
                self.pool.block_size if self.pool is not None else 0
            ),
            # full-co-batch ceiling basis for roofline.live_frac: the
            # replica's aggregate tok/s is judged against what the chip
            # allows at ALL lanes, not one (obs.prof.AnatomyTarget)
            "ceiling_batch": self.engine.lanes,
        }

    def stats(self) -> Dict[str, Any]:
        """Batching effectiveness for /stats: lane occupancy + how many
        decode steps actually coalesced (tok-per-weight-read is the whole
        point of this executor)."""
        out = self.spec_stats()
        with self._mu:
            out.update(
                mode="batched",
                lanes=self.engine.lanes,
                lanes_busy=self.engine.lanes - len(self.engine.free),
                prefill_tokens=self.prefill_tokens,
                **self._batcher.stats(),
            )
            if self.pool is not None:
                out["paged"] = self.pool.block_stats()
            if self.adapters is not None:
                out["adapters"] = self.adapters.stats()
            return out

    # -- node sweep surface (runtime/node.py:_sweep_loop) --------------------

    @property
    def sessions(self):
        return self

    def sweep(self) -> int:
        if not self._mu.acquire(blocking=False):
            return 0
        try:
            now = time.monotonic()
            stale = [
                s
                for s, t in self._last_used.items()
                if now - t > self.ttl_s and not self._inflight.get(s)
            ]
            for s in stale:
                self._drop(s)
            return len(stale)
        finally:
            self._mu.release()

    def ids(self):
        """Live session ids (gossip session-location advertising)."""
        with self._mu:
            return list(self._sessions)

    def kv_occupancy(self) -> float:
        """Fraction of the KV budget in use — the serving memory-pressure
        signal obs.devtel gauges per scrape. Paged: blocks used / blocks
        total; dense: filled positions / lanes x max_len."""
        with self._mu:
            if self.pool is not None:
                total = self.pool.num_blocks - 1
                return self.pool.blocks_used / float(total) if total else 0.0
            return sum(self.engine.lengths) / float(
                self.engine.lanes * self.max_len
            )

    def __len__(self) -> int:
        return len(self._sessions)

    def __contains__(self, session_id: str) -> bool:
        return session_id in self._sessions
