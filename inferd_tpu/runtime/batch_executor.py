"""Continuous-batching stage executor: concurrent sessions' decode steps
coalesce into ONE device step.

The reference serves strictly one request at a time per node (a lone
pipeline pass per token, /root/reference/petals/send_message.py:27-49 /
server.py:25-54); every session re-reads all the weights per token. This
executor keeps the node's `/forward` + client-side-sampling contract but
maps sessions to lanes of core.batch.BatchedEngine and batches the
single-token decode steps of whichever sessions arrive within a short
window — aggregate tok/s then scales with concurrency instead of dividing
by it (weights are read once per BATCHED step).

Concurrency design (process() runs on the node's worker thread pool):
  * decode steps (real_len == 1 at the session's frontier) enqueue into a
    pending batch; the FIRST arrival becomes the flusher — it waits up to
    `window_ms` for co-arrivals, takes the device lock, runs one batched
    step for every pending lane, and distributes each lane's logits to its
    waiting thread;
  * prefill chunks (multi-token or unknown session) run solo under the
    same device lock (per-lane cache writes, other lanes untouched);
  * whole-model executor: is_first and is_last (tokens in, last-token
    logits out) — like MeshExecutor it hosts a 1-stage swarm topology.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from inferd_tpu.config import ModelConfig
from inferd_tpu.core.batch import BatchedEngine
from inferd_tpu.core.generate import bucket_len

Params = Any


class CapacityError(RuntimeError):
    """All lanes are serving in-flight requests — transient backpressure
    (the node maps this to a retryable 503, unlike deterministic KV
    overflow which is a 409)."""


class _Pending:
    __slots__ = ("lane", "token", "event", "logits", "error")

    def __init__(self, lane: int, token: int):
        self.lane = lane
        self.token = token
        self.event = threading.Event()
        self.logits: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None


class BatchedExecutor:
    """Whole-model, lane-per-session executor with windowed decode batching.

    Node executor contract (runtime/node.py): process(session_id, payload)
    -> {"logits": [1, V], ...}; end_session(session_id).
    """

    is_first = True
    is_last = True

    def __init__(
        self,
        cfg: ModelConfig,
        params: Params,
        lanes: int = 8,
        max_len: int = 4096,
        window_ms: float = 3.0,
        session_ttl_s: float = 600.0,
    ):
        self.cfg = cfg
        self.engine = BatchedEngine(cfg, params, lanes=lanes, max_len=max_len)
        self.max_len = max_len
        self.window_s = window_ms / 1e3
        self.ttl_s = session_ttl_s

        self._dev_lock = threading.Lock()  # serializes device steps
        self._mu = threading.Lock()  # guards session/lane + pending state
        self._sessions: Dict[str, int] = {}  # session -> lane
        self._last_used: Dict[str, float] = {}
        self._inflight: Dict[str, int] = {}  # session -> active request count
        self._dying: Dict[int, str] = {}  # lane -> ended session awaiting drain
        self._pending: List[_Pending] = []
        self._flusher_active = False
        self._n_steps = 0  # batched decode steps executed
        self._n_step_tokens = 0  # sessions served across those steps

    # -- lane/session bookkeeping (call under self._mu) ----------------------

    def _lane_for(self, session_id: str, new_ok: bool) -> int:
        lane = self._sessions.get(session_id)
        if lane is not None:
            self._last_used[session_id] = time.monotonic()
            return lane
        if not new_ok:
            raise ValueError(
                f"session {session_id}: unknown session resumed mid-stream "
                "(cache evicted or node restarted)"
            )
        if not self.engine.free:
            # LRU-evict a session with NO request in flight (neither waiting
            # in the decode batch nor mid-prefill on another thread)
            victims = [
                s for s in self._sessions if not self._inflight.get(s)
            ]
            if not victims:
                raise CapacityError("all lanes busy with in-flight requests")
            oldest = min(victims, key=lambda s: self._last_used.get(s, 0.0))
            self._drop(oldest)
        lane = self.engine.free.pop()
        self._sessions[session_id] = lane
        self._last_used[session_id] = time.monotonic()
        return lane

    def _drop(self, session_id: str) -> None:
        lane = self._sessions.pop(session_id, None)
        self._last_used.pop(session_id, None)
        if lane is None:
            return
        # invalidate decode entries still waiting in the batch window — a
        # later flusher step must never write this lane on the old
        # session's behalf once a new session may own it
        still = []
        for p in self._pending:
            if p.lane == lane:
                p.error = ValueError(f"session {session_id} ended mid-request")
                p.event.set()
            else:
                still.append(p)
        self._pending[:] = still
        if self._inflight.get(session_id):
            # a request is mid-device-step (e.g. swapped into a flusher
            # batch): defer the free until it drains, else a new claimant
            # would share the lane with the stale write
            self._dying[lane] = session_id
        else:
            self.engine.lengths[lane] = 0
            self.engine.free.append(lane)

    # -- executor contract ---------------------------------------------------

    def process(self, session_id: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        toks = np.asarray(payload["tokens"], dtype=np.int32)
        if toks.ndim != 2 or toks.shape[0] != 1:
            raise ValueError(f"batched stage expects tokens [1, S], got {toks.shape}")
        start_pos = int(payload.get("start_pos", 0))
        real_len = int(payload.get("real_len", toks.shape[1]))

        with self._mu:
            if self._inflight.get(session_id):
                # a duplicate/replayed request racing the original would
                # pass the frontier check and double-advance the lane
                raise ValueError(
                    f"session {session_id}: concurrent request (one step at "
                    "a time per session)"
                )
            lane = self._lane_for(session_id, new_ok=start_pos == 0)
            have = self.engine.lengths[lane]
            if start_pos == 0 and have:
                # session restart under the same id: reset the lane
                self.engine.lengths[lane] = 0
                have = 0
            if start_pos != have:
                raise ValueError(
                    f"session {session_id}: start_pos {start_pos} != cache "
                    f"length {have} (out-of-order or replayed chunk)"
                )
            if start_pos + real_len > self.max_len:
                raise BufferError(
                    f"session {session_id}: KV overflow "
                    f"({start_pos}+{real_len} > {self.max_len})"
                )
            self._inflight[session_id] = 1

        try:
            if real_len == 1 and start_pos > 0:
                logits = self._decode_batched(session_id, lane, int(toks[0, 0]))
            else:
                logits = self._prefill_solo(lane, toks, start_pos, real_len)
        finally:
            with self._mu:
                self._inflight.pop(session_id, None)
                if self._dying.get(lane) == session_id:  # ended mid-request
                    del self._dying[lane]
                    self.engine.lengths[lane] = 0
                    self.engine.free.append(lane)
        return {
            "logits": logits[None, :],
            "real_len": real_len,
            "start_pos": start_pos,
        }

    def _prefill_solo(self, lane: int, toks: np.ndarray, start: int, n: int):
        import jax.numpy as jnp

        # cap the padded bucket so the in-jit dynamic_update_slice can never
        # clamp into older slots near the end of the cache (the stage
        # executor's _cache_for guards the same invariant); a capped tail
        # shape compiles its own program, which is rare and bounded
        b = min(bucket_len(toks.shape[1]), self.max_len - start)
        padded = np.zeros((1, b), np.int32)
        padded[0, : toks.shape[1]] = toks[0]
        with self._dev_lock:
            self.engine.cache, logits = self.engine._prefill_lane_logits(
                self.engine.params, self.engine.cache, jnp.asarray(padded),
                jnp.int32(lane), jnp.int32(start), jnp.int32(n),
            )
            out = np.asarray(logits, np.float32)
            # advance the lane BEFORE releasing the device lock: a flusher
            # snapshots lengths under the same lock order (_dev_lock, _mu),
            # so it can never scatter a decode write over these fresh rows
            # at the stale position
            with self._mu:
                self.engine.lengths[lane] = start + n  # real tokens only
            return out

    def _decode_batched(self, session_id: str, lane: int, token: int):
        entry = _Pending(lane, token)
        with self._mu:
            self._pending.append(entry)
            i_flush = not self._flusher_active
            if i_flush:
                self._flusher_active = True
            # co-arrival is only possible when another live session could
            # be decoding; a solo session should not pay the window latency
            co_possible = len(self._sessions) > 1

        if not i_flush:
            entry.event.wait(timeout=120.0)
            if entry.error is not None:
                raise entry.error
            if entry.logits is None:
                raise TimeoutError("batched decode flusher never completed")
            return entry.logits

        # flusher: give co-arriving sessions a beat, then run ONE step
        if co_possible:
            time.sleep(self.window_s)
        with self._dev_lock:
            with self._mu:
                batch, self._pending = self._pending, []
                self._flusher_active = False
                lens = list(self.engine.lengths)  # snapshot under _mu
            try:
                import jax.numpy as jnp
                L = self.engine.lanes
                toks = [0] * L
                for p in batch:
                    toks[p.lane] = p.token
                self.engine.cache, logits = self.engine._decode_logits(
                    self.engine.params, self.engine.cache,
                    jnp.asarray(toks, jnp.int32), jnp.asarray(lens, jnp.int32),
                )
                out = np.asarray(logits, np.float32)
                with self._mu:
                    for p in batch:
                        self.engine.lengths[p.lane] += 1
                    self._n_steps += 1
                    self._n_step_tokens += len(batch)
                for p in batch:
                    p.logits = out[p.lane]
                    p.event.set()
                return entry.logits
            except Exception as e:
                for p in batch:
                    p.error = e
                    p.event.set()
                raise

    def end_session(self, session_id: str) -> None:
        with self._mu:
            self._drop(session_id)

    def stats(self) -> Dict[str, Any]:
        """Batching effectiveness for /stats: lane occupancy + how many
        decode steps actually coalesced (tok-per-weight-read is the whole
        point of this executor)."""
        with self._mu:
            return {
                "mode": "batched",
                "lanes": self.engine.lanes,
                "lanes_busy": self.engine.lanes - len(self.engine.free),
                "batched_steps": self._n_steps,
                "batched_tokens": self._n_step_tokens,
                "mean_batch": round(self._n_step_tokens / self._n_steps, 3)
                if self._n_steps
                else 0.0,
            }

    # -- node sweep surface (runtime/node.py:_sweep_loop) --------------------

    @property
    def sessions(self):
        return self

    def sweep(self) -> int:
        if not self._mu.acquire(blocking=False):
            return 0
        try:
            now = time.monotonic()
            waiting = {p.lane for p in self._pending}
            stale = [
                s
                for s, t in self._last_used.items()
                if now - t > self.ttl_s and self._sessions.get(s) not in waiting
            ]
            for s in stale:
                self._drop(s)
            return len(stale)
        finally:
            self._mu.release()

    def __len__(self) -> int:
        return len(self._sessions)

    def __contains__(self, session_id: str) -> bool:
        return session_id in self._sessions
